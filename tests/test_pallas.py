"""Pallas fused Sherman-Morrison z-solve vs the XLA reference path
(interpret mode on CPU; compiled path exercised on TPU by bench)."""
import jax
import jax.numpy as jnp
import numpy as np

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)
from ccsc_code_iccv2017_tpu.ops import freq_solvers, pallas_kernels


def _rand_problem(r, K, F, N):
    dhat = (r.normal(size=(K, F)) + 1j * r.normal(size=(K, F))).astype(
        np.complex64
    )
    xi1 = (r.normal(size=(N, F)) + 1j * r.normal(size=(N, F))).astype(
        np.complex64
    )
    xi2 = (
        r.normal(size=(N, K, F)) + 1j * r.normal(size=(N, K, F))
    ).astype(np.complex64)
    return dhat, xi1, xi2


def test_pallas_solve_matches_xla():
    r = np.random.default_rng(0)
    K, F, N, rho = 20, 700, 3, 0.7  # K, F deliberately not tile-aligned
    dhat, xi1, xi2 = _rand_problem(r, K, F, N)
    kern = freq_solvers.precompute_z_kernel(jnp.asarray(dhat)[:, None, :], rho)
    ref = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho
    )
    out = pallas_kernels.solve_z_rank1_pallas(
        jnp.asarray(dhat),
        jnp.asarray(xi1),
        jnp.asarray(xi2),
        rho,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_pallas_solve_matches_xla_with_extra_diag():
    """The generalized Gamma = diag(rho + extra) case: the Poisson
    solver's gradient-regularized dirac channel
    (admm_solve_conv_poisson.m:165-176)."""
    r = np.random.default_rng(1)
    K, F, N, rho = 5, 600, 2, 1.3
    dhat, xi1, xi2 = _rand_problem(r, K, F, N)
    extra = np.zeros((K, F), np.float32)
    extra[-1] = r.uniform(0.0, 3.0, F)  # dirac channel regularization
    kern = freq_solvers.precompute_z_kernel(
        jnp.asarray(dhat)[:, None, :], rho, extra_diag=jnp.asarray(extra)
    )
    ref = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho
    )
    out = pallas_kernels.solve_z_rank1_pallas(
        jnp.asarray(dhat),
        jnp.asarray(xi1),
        jnp.asarray(xi2),
        rho,
        dinv=kern.dinv,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    # and through the dispatching entry point
    out2 = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho,
        use_pallas=True,
    )
    np.testing.assert_allclose(
        np.asarray(out2), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_learn_use_pallas_matches():
    """Full outer step with the Pallas z-solve == einsum path."""
    geom = ProblemGeom((3, 3), 4)
    L, ni, size = 2, 2, 8
    fg = common.FreqGeom.create(geom, (size, size))
    b = jax.random.normal(jax.random.PRNGKey(1), (L, ni, size, size))
    state = learn_mod.init_state(jax.random.PRNGKey(0), geom, fg, L, ni)

    def run(use_pallas):
        cfg = LearnConfig(
            max_it=1, max_it_d=2, max_it_z=3, num_blocks=L,
            rho_d=50.0, rho_z=2.0, verbose="none", use_pallas=use_pallas,
        )
        step = jax.jit(
            lambda s, bb: learn_mod.outer_step(
                s, bb, geom=geom, cfg=cfg, fg=fg, num_blocks=L,
                axis_name=None,
            )
        )
        out, _ = step(state, b)
        return out

    a, p = run(False), run(True)
    for name, x, y in zip(learn_mod.LearnState._fields, a, p):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=1e-5, rtol=1e-5,
            err_msg=name,
        )


def test_reconstruct_use_pallas_matches():
    r = np.random.default_rng(2)
    geom = ProblemGeom((3, 3), 4)
    prob = ReconstructionProblem(geom)
    b = r.uniform(0.1, 1.0, (2, 10, 10)).astype(np.float32)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    mask = (r.uniform(size=b.shape) > 0.4).astype(np.float32)

    def run(use_pallas):
        cfg = SolveConfig(
            max_it=4, tol=0.0, verbose="none", use_pallas=use_pallas
        )
        return reconstruct(
            jnp.asarray(b), jnp.asarray(d), prob, cfg,
            mask=jnp.asarray(mask),
        )

    a, p = run(False), run(True)
    np.testing.assert_allclose(
        np.asarray(a.z), np.asarray(p.z), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(a.recon), np.asarray(p.recon), atol=1e-5, rtol=1e-5
    )
