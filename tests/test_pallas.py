"""ops.pallas_kernels vs the einsum z-solve.

The per-solve Pallas kernel measured 0.93x the einsum path on the v5e
(onchip_r4.jsonl 'pallas' arm) and was demoted in r5; r10 re-admitted
it as a measured serve-solve autotuner arm (tune.space SOLVE_KNOBS)
behind the numerics guard, and freq_solvers.solve_z routes
`use_pallas=True` to it for the W == 1 / filter-unsharded /
static-rho case. The kernel is an INDEPENDENT implementation of the
rank-1 Sherman-Morrison solve
(admm_solve_conv2D_weighted_sampling.m:170-190) — these tests check
the two against each other (interpret mode on CPU), plus the routing
contract: the routed call agrees with the einsum path to float
tolerance and IS the kernel bit-for-bit; non-routable calls stay
bit-identical to the einsum path. The learners' production Pallas
path remains the fused whole-iteration kernel (ops.pallas_fused_z,
tests/test_pallas_fused.py).
"""
import jax.numpy as jnp
import numpy as np

from ccsc_code_iccv2017_tpu.ops import freq_solvers, pallas_kernels


def _rand_problem(r, K, F, N):
    dhat = (r.normal(size=(K, F)) + 1j * r.normal(size=(K, F))).astype(
        np.complex64
    )
    xi1 = (r.normal(size=(N, F)) + 1j * r.normal(size=(N, F))).astype(
        np.complex64
    )
    xi2 = (
        r.normal(size=(N, K, F)) + 1j * r.normal(size=(N, K, F))
    ).astype(np.complex64)
    return dhat, xi1, xi2


def test_pallas_solve_matches_xla():
    r = np.random.default_rng(0)
    K, F, N, rho = 20, 700, 3, 0.7  # K, F deliberately not tile-aligned
    dhat, xi1, xi2 = _rand_problem(r, K, F, N)
    kern = freq_solvers.precompute_z_kernel(jnp.asarray(dhat)[:, None, :], rho)
    ref = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho
    )
    out = pallas_kernels.solve_z_rank1_pallas(
        jnp.asarray(dhat),
        jnp.asarray(xi1),
        jnp.asarray(xi2),
        rho,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_pallas_solve_matches_xla_with_extra_diag():
    """The generalized Gamma = diag(rho + extra) case: the Poisson
    solver's gradient-regularized dirac channel
    (admm_solve_conv_poisson.m:165-176)."""
    r = np.random.default_rng(1)
    K, F, N, rho = 5, 600, 2, 1.3
    dhat, xi1, xi2 = _rand_problem(r, K, F, N)
    extra = np.zeros((K, F), np.float32)
    extra[-1] = r.uniform(0.0, 3.0, F)  # dirac channel regularization
    kern = freq_solvers.precompute_z_kernel(
        jnp.asarray(dhat)[:, None, :], rho, extra_diag=jnp.asarray(extra)
    )
    ref = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho
    )
    out = pallas_kernels.solve_z_rank1_pallas(
        jnp.asarray(dhat),
        jnp.asarray(xi1),
        jnp.asarray(xi2),
        rho,
        dinv=kern.dinv,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_use_pallas_routes_to_the_kernel():
    """At W == 1 / unsharded / static rho, use_pallas=True routes:
    the result is the Pallas kernel's output bit-for-bit and agrees
    with the einsum path to the kernel's float tolerance (the arm is
    non-exact — that is why the autotuner guards it)."""
    r = np.random.default_rng(2)
    dhat, xi1, xi2 = _rand_problem(r, 6, 80, 2)
    kern = freq_solvers.precompute_z_kernel(
        jnp.asarray(dhat)[:, None, :], 0.9
    )
    a = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), 0.9
    )
    b = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), 0.9,
        use_pallas=True,
    )
    direct = pallas_kernels.solve_z_rank1_pallas(
        jnp.asarray(dhat), jnp.asarray(xi1), jnp.asarray(xi2), 0.9,
        dinv=kern.dinv, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(b), np.asarray(direct))
    np.testing.assert_allclose(
        np.asarray(b), np.asarray(a), atol=2e-5, rtol=2e-5
    )


def test_use_pallas_falls_back_bit_identical():
    """Outside the kernel's coverage (here: a traced rho, as inside a
    jitted solve whose rho is a tracer) the einsum path runs and the
    result is bit-identical to use_pallas=False."""
    r = np.random.default_rng(3)
    dhat, xi1, xi2 = _rand_problem(r, 6, 80, 2)
    kern = freq_solvers.precompute_z_kernel(
        jnp.asarray(dhat)[:, None, :], 0.9
    )
    rho_traced = jnp.float32(0.9)  # not a python float -> no route
    a = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho_traced
    )
    freq_solvers._use_pallas_warned = True  # silence; test_obs covers it
    b = freq_solvers.solve_z(
        kern, jnp.asarray(xi1)[:, None, :], jnp.asarray(xi2), rho_traced,
        use_pallas=True,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
