"""Request-level tracing, SLO histograms, and the live metrics
surface (ISSUE 9): utils.trace span emission/reassembly, the
serve.slo streaming histograms + breach monitor, serve.metricsd's
Prometheus endpoint and atomic snapshot, obs.EventTail incremental
reads, and the xprof_report degrade path."""
import importlib.util
import json
import os
import sys
import time
import types
import urllib.request

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.serve import metricsd, slo
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils import trace as trace_util

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ span assembly


def _collector():
    evs = []

    def emit(type_, **fields):
        evs.append({"t": time.time(), "type": type_, **fields})

    return evs, emit


def test_span_pair_assembles_complete():
    evs, emit = _collector()
    tid = trace_util.new_trace_id()
    root = trace_util.start_span(
        emit, trace_id=tid, span="request", ts=100.0
    )
    trace_util.emit_span(
        emit, trace_id=tid, span="solve", parent_span=root,
        t_start=100.2, t_end=100.7, replica_id=1, bucket="2@12x12",
    )
    trace_util.end_span(
        emit, trace_id=tid, span="request", span_id=root,
        status="ok", ts=101.0, t_start=100.0,
    )
    traces = trace_util.assemble(evs)
    assert list(traces) == [tid]
    tr = traces[tid]
    assert tr.complete
    assert tr.root.dur_ms == pytest.approx(1000.0)
    solve = tr.by_name("solve")[0]
    assert solve.parent_span == root
    assert solve.replica_id == 1
    assert solve.fields["bucket"] == "2@12x12"
    assert tr.duration_ms == pytest.approx(1000.0)
    txt = trace_util.render_timeline(tr)
    assert "request" in txt and "solve" in txt and "ok" in txt


def test_orphans_and_dangling_parents_detected():
    evs, emit = _collector()
    tid = "t1"
    root = trace_util.start_span(
        emit, trace_id=tid, span="request", ts=1.0
    )
    # start with no end -> orphan
    trace_util.start_span(
        emit, trace_id=tid, span="queue", parent_span=root, ts=1.1
    )
    # end with no start -> orphan
    trace_util.end_span(
        emit, trace_id=tid, span="attempt", span_id="lonely",
        parent_span=root, status="ok", ts=1.5,
    )
    # dangling parent ref -> gap
    trace_util.emit_span(
        emit, trace_id=tid, span="solve", parent_span="no-such-span",
        t_start=1.2, t_end=1.3,
    )
    tr = trace_util.assemble(evs)[tid]
    assert not tr.complete
    assert len(tr.orphans) == 3  # open root + open queue + lonely end
    assert [s.span_id for s in tr.unparented] != []
    txt = trace_util.render_timeline(tr)
    assert "INCOMPLETE" in txt


def test_slowest_ranks_complete_traces_only():
    evs, emit = _collector()
    for i, dur in enumerate((0.5, 2.0, 1.0)):
        trace_util.emit_span(
            emit, trace_id=f"t{i}", span="request",
            t_start=10.0, t_end=10.0 + dur,
        )
    trace_util.start_span(  # incomplete trace never ranks
        emit, trace_id="t9", span="request", ts=0.0
    )
    traces = trace_util.assemble(evs)
    ranked = trace_util.slowest(traces, 2)
    assert [t.trace_id for t in ranked] == ["t1", "t2"]


# ---------------------------------------------------------- histogram


def test_histogram_percentile_within_one_bucket_width():
    r = np.random.default_rng(0)
    vals = list(np.abs(r.normal(50.0, 40.0, 500)) + 0.2)
    h = slo.Histogram.of(vals)
    assert h.n == 500
    for q in (0.5, 0.9, 0.99):
        exact = obs.percentile(vals, q)
        got = h.percentile(q)
        assert got is not None
        assert abs(got - exact) <= h.bucket_width_ms(exact) + 1e-9
    assert h.percentile(1.0) == pytest.approx(h.max_ms)


def test_histogram_empty_merge_and_snapshot_roundtrip():
    h = slo.Histogram()
    assert h.percentile(0.5) is None
    h.observe(3.0)
    h2 = slo.Histogram.of([100.0, 200.0])
    h.merge(h2)
    assert h.n == 3
    back = slo.from_snapshot(h.snapshot())
    assert back.counts == h.counts
    assert back.percentile(0.5) == h.percentile(0.5)
    with pytest.raises(ValueError):
        h.merge(slo.Histogram(bounds=(1.0, 2.0)))


def test_percentile_sorts_internally():
    # the historical contract required pre-sorted input with no
    # guard; unsorted callers now get the correct answer
    assert obs.percentile([5.0, 1.0, 3.0], 0.5) == 3.0
    assert obs.percentile([], 0.5) is None


def test_slo_monitor_breach_and_snapshot():
    mon = slo.SloMonitor(targets={0.99: 10.0}, check_s=0.0)
    for _ in range(20):
        mon.observe("total", 50.0)
    breaches, snaps = mon.tick()
    assert len(breaches) == 1
    br = breaches[0]
    assert br["quantile"] == 0.99 and br["target_ms"] == 10.0
    assert br["observed_ms"] > 10.0
    assert [s["phase"] for s in snaps] == ["total"]
    # no NEW observations -> the same breach does not re-fire
    breaches2, _ = mon.tick()
    assert breaches2 == []
    mon.observe("total", 60.0)
    breaches3, _ = mon.tick()
    assert len(breaches3) == 1
    # raw_snapshots must not consume the breach bookkeeping
    mon.observe("total", 70.0)
    assert mon.raw_snapshots()
    assert len(mon.tick()[0]) == 1


def test_breach_check_is_conservative_to_bucket_width():
    """A target that merely falls INSIDE the rank bucket must not
    breach: the reported percentile is the bucket upper edge (can
    overstate by a width), so the check compares the LOWER edge —
    only a provable violation fires (and burns the one-shot xprof)."""
    mon = slo.SloMonitor(targets={0.5: 100.0}, check_s=0.0)
    for _ in range(9):
        mon.observe("total", 95.0)  # true p50 = 95: SLO met
    mon.observe("total", 200.0)  # keeps max_ms off the clamp
    breaches, _ = mon.tick()
    assert breaches == [], breaches
    mon2 = slo.SloMonitor(targets={0.5: 40.0}, check_s=0.0)
    for _ in range(10):
        mon2.observe("total", 95.0)  # whole bucket above the target
    b2, _ = mon2.tick()
    assert len(b2) == 1 and b2[0]["observed_ms"] > 40.0


def test_resolve_targets_env_fallback(monkeypatch):
    monkeypatch.setenv("CCSC_SLO_P99_MS", "25.5")
    t = slo.resolve_targets(None, None)
    assert t == {0.99: 25.5}
    assert slo.resolve_targets(10.0, 20.0) == {0.5: 10.0, 0.99: 20.0}


# ---------------------------------------------------------- EventTail


def test_event_tail_incremental_and_torn_lines(tmp_path):
    p = tmp_path / "events-p00000.jsonl"
    p.write_text('{"t": 1.0, "type": "step", "it": 1}\n')
    tail = obs.EventTail(str(tmp_path))
    first = tail.poll()
    assert [e["it"] for e in first] == [1]
    assert tail.poll() == []  # nothing new
    with open(p, "a") as f:
        f.write('{"t": 2.0, "type": "step", "it": 2}\n')
        f.write('{"t": 3.0, "type": "st')  # torn trailing line
    second = tail.poll()
    assert [e["it"] for e in second] == [2]  # torn line left alone
    with open(p, "a") as f:
        f.write('ep", "it": 3}\n')
    third = tail.poll()
    assert [e["it"] for e in third] == [3]  # completed line consumed


def test_event_tail_discovers_new_files_and_recurses(tmp_path):
    (tmp_path / "events-p00000.jsonl").write_text(
        '{"t": 1.0, "type": "step", "it": 1}\n'
    )
    tail = obs.EventTail(str(tmp_path), recursive=True)
    assert len(tail.poll()) == 1
    sub = tmp_path / "replica-00"
    sub.mkdir()
    (sub / "events-p00000.jsonl").write_text(
        '{"t": 2.0, "type": "step", "it": 2}\n'
    )
    recs = tail.poll()
    assert [e["it"] for e in recs] == [2]


def test_heartbeat_tail_rides_event_tail(tmp_path):
    from ccsc_code_iccv2017_tpu.utils.watchdog import _HeartbeatTail

    p = tmp_path / "events-p00000.jsonl"
    p.write_text(
        '{"t": 10.0, "type": "heartbeat", "host": 0, "step": 1}\n'
        '{"t": 200.0, "type": "heartbeat", "host": 1, "step": 9}\n'
        '{"t": 201.0, "type": "step", "it": 9}\n'
    )
    ht = _HeartbeatTail(str(tmp_path))
    stale = ht.stale_peers(120.0)
    assert [s["host"] for s in stale] == [0]
    # incremental: appending a fresh heartbeat un-stales host 0
    with open(p, "a") as f:
        f.write('{"t": 202.0, "type": "heartbeat", "host": 0, "step": 2}\n')
    assert ht.stale_peers(120.0) == []


# ----------------------------------------------------------- metricsd


def test_render_prometheus_shapes():
    h = slo.Histogram.of([1.0, 5.0, 500.0])
    text = metricsd.render_prometheus(
        {
            "counters": {"requests_total": 3},
            "gauges": {"queue_depth": 1},
            "histograms": [
                ("latency_ms", {"phase": "total"}, h.snapshot())
            ],
        }
    )
    assert "# TYPE ccsc_requests_total counter" in text
    assert "ccsc_requests_total 3" in text
    assert "ccsc_queue_depth 1" in text
    assert 'ccsc_latency_ms_bucket{le="+Inf",phase="total"} 3' in text
    assert 'ccsc_latency_ms_count{phase="total"} 3' in text
    # cumulative buckets are monotone
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("ccsc_latency_ms_bucket")
    ]
    assert cums == sorted(cums) and cums[-1] == 3


def test_metricsd_http_and_snapshot(tmp_path):
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        return {
            "counters": {"requests_total": 7},
            "gauges": {},
            "histograms": [],
        }

    snap = tmp_path / "metrics.prom"
    md = metricsd.MetricsD(
        source, port=0, snapshot_path=str(snap), interval_s=0.05
    ).start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{md.port}/metrics", timeout=10
        ).read().decode()
        assert "ccsc_requests_total 7" in body
        assert snap.exists()
        assert "ccsc_requests_total 7" in snap.read_text()
    finally:
        md.stop()
    # threads are joined — no ccsc-metricsd thread survives stop()
    import threading

    assert not any(
        t.name.startswith("ccsc-metricsd") and t.is_alive()
        for t in threading.enumerate()
    )


def test_stream_metrics_counts_from_dir(tmp_path):
    p = tmp_path / "events-p00000.jsonl"
    recs = [
        {"t": 1.0, "type": "fleet_request", "replica_id": 0,
         "trace_id": "t", "key": "k1", "latency_ms": 5.0},
        {"t": 2.0, "type": "fleet_request", "replica_id": 0,
         "trace_id": "t", "key": "k2", "latency_ms": 6.0},
        {"t": 3.0, "type": "fleet_admission_reject", "replica_id": None,
         "queue_depth": 4, "ceiling": 4, "rung": "reject",
         "retry_after_s": 1.0},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    sm = metricsd.StreamMetrics(str(tmp_path))
    m = sm()
    assert m["counters"]["requests_total"] == 2
    assert m["counters"]["rejected_total"] == 1
    text = metricsd.render_prometheus(m)
    assert "ccsc_requests_total 2" in text


def test_stream_metrics_fleet_dir_never_double_counts(tmp_path):
    """A fleet dir carries BOTH records for one delivery — the
    replica's serve_request (earlier t) and the fleet's
    fleet_request. Fleet mode is latched STRUCTURALLY from the
    replica-NN subdirs, so the counter is the delivered count from
    the first scrape on: never serve+fleet summed, and never a
    non-monotone flip from the engine-side count to the (briefly
    lower) fleet count — a Prometheus counter must not decrease."""
    sub = tmp_path / "replica-00"
    sub.mkdir()
    (sub / "events-p00000.jsonl").write_text(
        json.dumps(
            {"t": 1.0, "type": "serve_request", "replica_id": 0,
             "trace_id": "t1", "bucket": "2@12x12",
             "latency_ms": 4.0, "iters": 3}
        ) + "\n"
    )
    top = tmp_path / "events-p00000.jsonl"
    sm = metricsd.StreamMetrics(str(tmp_path))
    # scrape BETWEEN dispatch and delivery: the fleet's delivered
    # count (0) is authoritative for a fleet dir
    assert sm()["counters"]["requests_total"] == 0
    top.write_text(
        json.dumps(
            {"t": 1.01, "type": "fleet_request", "replica_id": 0,
             "trace_id": "t1", "key": "k1", "latency_ms": 5.0}
        ) + "\n"
    )
    assert sm()["counters"]["requests_total"] == 1


# ------------------------------------------- live engine + fleet e2e

jnp = pytest.importorskip("jax.numpy")
from ccsc_code_iccv2017_tpu.models.reconstruct import (  # noqa: E402
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet  # noqa: E402


def _bank(k=4, s=3, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=4, tol=0.0,
        verbose="none", track_objective=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _reqs(n, side=12, seed=1):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = r.random((side, side)).astype(np.float32)
        m = (r.random((side, side)) < 0.5).astype(np.float32)
        out.append((x, m))
    return out


def test_standalone_engine_emits_complete_traces(tmp_path):
    d = _bank()
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none",
        metrics_dir=str(tmp_path),
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    eng = CodecEngine(d, ReconstructionProblem(geom), _cfg(), scfg)
    try:
        futs = [eng.submit(x * m, mask=m) for x, m in _reqs(3)]
        [f.result(timeout=120) for f in futs]
        st = eng.stats()
        assert st["n_requests"] == 3
        assert st["p99_latency_s"] is not None
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    sreqs = [e for e in events if e["type"] == "serve_request"]
    assert len(sreqs) == 3
    assert all(e.get("trace_id") for e in sreqs)
    traces = trace_util.assemble(events)
    assert len(traces) == 3
    for tr in traces.values():
        assert tr.complete, [
            (s.name, s.closed) for s in tr.spans.values()
        ]
        assert {s.name for s in tr.spans.values()} == {
            "request", "engine_queue", "solve",
        }
    # closing histogram flush: offline percentiles within one bucket
    hists = [e for e in events if e["type"] == "slo_histogram"]
    assert {h["phase"] for h in hists} >= {"total", "queue", "solve"}
    last_total = [h for h in hists if h["phase"] == "total"][-1]
    back = slo.from_snapshot(last_total)
    assert back.n == 3
    # snapshot max_ms rounds to 1e-3 ms — equal to that precision
    assert back.percentile(0.99) / 1e3 == pytest.approx(
        st["p99_latency_s"], abs=1e-5
    )


def test_engine_slo_breach_arms_one_shot_xprof(tmp_path):
    d = _bank()
    prof = tmp_path / "prof"
    scfg = ServeConfig(
        buckets=((1, (12, 12)),), max_wait_ms=0.0, verbose="none",
        metrics_dir=str(tmp_path / "m"),
        slo_p99_ms=0.001,  # everything breaches
        slo_check_s=0.001,
        slo_profile_dir=str(prof),
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    eng = CodecEngine(d, ReconstructionProblem(geom), _cfg(), scfg)
    try:
        for x, m in _reqs(3):
            eng.reconstruct(x * m, mask=m, timeout=120)
            time.sleep(0.01)  # let the check cadence elapse
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path / "m"))
    breaches = [e for e in events if e["type"] == "slo_breach"]
    assert breaches, "a 1us p99 target must breach"
    assert breaches[0]["observed_ms"] > breaches[0]["target_ms"]
    profiles = [e for e in events if e["type"] == "slo_profile"]
    assert len(profiles) == 1, "the capture is one-shot"
    assert profiles[0]["trace_dir"] == str(prof)
    assert os.path.isdir(prof) and os.listdir(prof)


def test_fleet_metricsd_scrape_counts_exactly(tmp_path):
    d = _bank()
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(), scfg,
        FleetConfig(
            replicas=1, min_queue_depth=64, verbose="none",
            metrics_dir=str(tmp_path), metricsd_port=0,
            heartbeat_s=0.2, health_interval_s=0.05,
        ),
    )
    try:
        assert fleet._metricsd is not None and fleet._metricsd.port
        n = 6
        futs = [
            fleet.submit(x * m, mask=m, key=f"m{i}")
            for i, (x, m) in enumerate(_reqs(n, seed=3))
        ]
        [f.result(timeout=180) for f in futs]
        url = f"http://127.0.0.1:{fleet._metricsd.port}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        # ISSUE 9 acceptance (c): the live scrape's request counter
        # equals the number of served requests EXACTLY
        assert f"ccsc_requests_total {n}" in body
        assert "ccsc_live_replicas 1" in body
        assert 'ccsc_latency_ms_bucket{le="+Inf",phase="total"}' in body
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path), recursive=True)
    md = [e for e in events if e["type"] == "fleet_metricsd"]
    assert md and md[0]["port"] == fleet._metricsd.port
    # the atomic snapshot (default path under the metrics dir) holds
    # the final exposition for scrape-less readers
    snap = os.path.join(str(tmp_path), "metrics.prom")
    assert os.path.exists(snap)
    with open(snap) as f:
        assert f"ccsc_requests_total {n}" in f.read()


def test_resolve_endpoint_chain(monkeypatch, tmp_path):
    """One resolution chain shared by the fleet and the standalone
    CLI: explicit > CCSC_METRICSD_* env > metrics_dir default."""
    monkeypatch.delenv("CCSC_METRICSD_PORT", raising=False)
    monkeypatch.delenv("CCSC_METRICSD_SNAPSHOT", raising=False)
    assert metricsd.resolve_endpoint(None, None, None) == (None, None)
    assert metricsd.resolve_endpoint(0, None, str(tmp_path)) == (
        0, os.path.join(str(tmp_path), "metrics.prom"),
    )
    # a snapshot request WITHOUT a port is honored: snapshot-only
    # mode (scrape-less environments are the snapshot's whole point)
    assert metricsd.resolve_endpoint(None, "/s.prom", None) == (
        None, "/s.prom",
    )
    monkeypatch.setenv("CCSC_METRICSD_PORT", "9104")
    monkeypatch.setenv("CCSC_METRICSD_SNAPSHOT", "/x/y.prom")
    assert metricsd.resolve_endpoint(None, None, None) == (
        9104, "/x/y.prom",
    )
    assert metricsd.resolve_endpoint(None, "/z.prom", None)[1] == "/z.prom"


def test_metricsd_start_failure_does_not_leak_server(tmp_path):
    """If the initial snapshot write fails after the HTTP server
    started, start() must shut the server down before re-raising —
    callers catch the exception and drop the instance, and an
    ownerless daemon squatting the port would block every fleet
    rebuild with EADDRINUSE."""
    import threading

    bad = tmp_path / "f"
    bad.write_text("not a dir")  # makedirs under a FILE raises
    md = metricsd.MetricsD(
        lambda: {"counters": {}, "gauges": {}, "histograms": []},
        port=0, snapshot_path=str(bad / "x" / "m.prom"),
    )
    with pytest.raises(Exception):
        md.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
        t.name.startswith("ccsc-metricsd") and t.is_alive()
        for t in threading.enumerate()
    ):
        time.sleep(0.05)
    assert not any(
        t.name.startswith("ccsc-metricsd") and t.is_alive()
        for t in threading.enumerate()
    )


def test_metricsd_snapshot_only_mode(tmp_path):
    """port=None starts no HTTP server but still writes the atomic
    snapshot — the scrape-less deployment shape."""
    snap = tmp_path / "only.prom"
    md = metricsd.MetricsD(
        lambda: {"counters": {"requests_total": 4}, "gauges": {},
                 "histograms": []},
        port=None, snapshot_path=str(snap), interval_s=0.05,
    ).start()
    try:
        assert md.port is None
        assert "ccsc_requests_total 4" in snap.read_text()
    finally:
        md.stop()


def test_straggler_delivery_does_not_misattribute_attempt(tmp_path):
    """A recovered straggler that wins the delivery race after a
    requeue must not end the NEW owner's attempt span as its own
    'ok': the span keeps its owner's replica_id and closes
    'superseded' (the fleet_request record names the actual
    deliverer)."""
    from concurrent.futures import Future

    from ccsc_code_iccv2017_tpu.serve.fleet import _FleetRequest

    d = _bank()
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(), scfg,
        FleetConfig(
            replicas=1, min_queue_depth=64, verbose="none",
            metrics_dir=str(tmp_path),
            heartbeat_s=0.2, health_interval_s=0.05,
        ),
    )
    try:
        x, m = _reqs(1)[0]
        res = fleet.reconstruct(x * m, mask=m, key="real", timeout=180)
        # a request whose OPEN attempt span belongs to replica 7,
        # delivered by the straggler worker of replica 0
        req = _FleetRequest(
            key="race", b=x * m, mask=m, smooth_init=None,
            x_orig=None, future=Future(),
            t_submit=time.time(), attempts=2,
            trace_id="racetrace", root_span="root1",
            attempt_span="att-owner7", attempt_rep=7,
            attempt_t=time.time(),
        )
        with fleet._cv:
            fleet._index["race"] = req
        fleet._deliver(fleet._replicas[0], req, res)
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path))
    end = [
        e for e in events
        if e["type"] == "span_end" and e.get("span_id") == "att-owner7"
    ]
    assert len(end) == 1
    assert end[0]["replica_id"] == 7
    assert end[0]["status"] == "superseded"
    # the delivery record still names the replica that delivered
    fr = [
        e for e in events
        if e["type"] == "fleet_request" and e["key"] == "race"
    ]
    assert fr and fr[0]["replica_id"] == 0


def test_fleet_stats_percentiles_come_from_histogram(tmp_path):
    d = _bank()
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(), scfg,
        FleetConfig(
            replicas=1, min_queue_depth=64, verbose="none",
            heartbeat_s=0.2, health_interval_s=0.05,
        ),
    )
    try:
        for i, (x, m) in enumerate(_reqs(5, seed=5)):
            fleet.reconstruct(x * m, mask=m, key=f"s{i}", timeout=180)
        st = fleet.stats()
        exact_ms = sorted(v * 1e3 for v in fleet._latencies)
        assert st["n_requests"] == 5
        for key, q in (("p50_latency_s", 0.5), ("p99_latency_s", 0.99)):
            got_ms = st[key] * 1e3
            ex = obs.percentile(exact_ms, q)
            width = slo.Histogram.of(exact_ms).bucket_width_ms(ex)
            assert abs(got_ms - ex) <= width + 1e-6
    finally:
        fleet.close()


# ------------------------------------------------------- xprof_report


def _load_xprof_report():
    spec = importlib.util.spec_from_file_location(
        "xprof_report", os.path.join(REPO, "scripts", "xprof_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Ev:
    def __init__(self, mid, ps):
        self.metadata_id = mid
        self.duration_ps = ps


class _Line:
    def __init__(self, name, events):
        self.name = name
        self.events = events


class _Meta:
    def __init__(self, id_, name):
        self.id = id_
        self.name = name


class _Plane:
    def __init__(self, name, lines, metadata):
        self.name = name
        self.lines = lines
        self.event_metadata = {m.id: m for m in metadata}


class _XSpace:
    """Synthetic XPlane stand-in: 'ParseFromString' reads our JSON
    fixture format instead of the real proto wire format."""

    def __init__(self):
        self.planes = []

    def ParseFromString(self, data):  # noqa: N802 - proto API
        spec = json.loads(data.decode("utf-8"))
        for pl in spec["planes"]:
            metas = [
                _Meta(m["id"], m["name"]) for m in pl["metadata"]
            ]
            lines = [
                _Line(
                    ln["name"],
                    [_Ev(e["mid"], e["ps"]) for e in ln["events"]],
                )
                for ln in pl["lines"]
            ]
            self.planes.append(_Plane(pl["name"], lines, metas))


def _install_fake_xplane(monkeypatch):
    leaf = types.ModuleType("xplane_pb2")
    leaf.XSpace = _XSpace
    mods = {}
    for name in (
        "tensorflow",
        "tensorflow.tsl",
        "tensorflow.tsl.profiler",
        "tensorflow.tsl.profiler.protobuf",
    ):
        mods[name] = types.ModuleType(name)
    mods["tensorflow.tsl.profiler.protobuf"].xplane_pb2 = leaf
    mods["tensorflow.tsl.profiler.protobuf.xplane_pb2"] = leaf
    for name, mod in mods.items():
        monkeypatch.setitem(sys.modules, name, mod)


def test_xprof_report_synthetic_xplane(tmp_path, monkeypatch):
    _install_fake_xplane(monkeypatch)
    fixture = {
        "planes": [
            {
                "name": "/device:TPU:0",
                "metadata": [
                    {"id": 1, "name": "fusion.1"},
                    {"id": 2, "name": "copy.2"},
                ],
                "lines": [
                    {
                        "name": "XLA Modules",
                        "events": [{"mid": 1, "ps": 90_000_000_000}],
                    },
                    {
                        "name": "XLA Ops",
                        "events": [
                            {"mid": 1, "ps": 30_000_000_000},
                            {"mid": 2, "ps": 10_000_000_000},
                        ],
                    },
                ],
            },
            {
                "name": "Host Threads",
                "metadata": [{"id": 9, "name": "python"}],
                "lines": [
                    {
                        "name": "threads",
                        "events": [{"mid": 9, "ps": 999_000_000_000}],
                    }
                ],
            },
        ]
    }
    sub = tmp_path / "plugins" / "profile"
    sub.mkdir(parents=True)
    (sub / "host.xplane.pb").write_bytes(
        json.dumps(fixture).encode()
    )
    xr = _load_xprof_report()
    out = xr.summarize(str(tmp_path))
    assert out["xprof"] == "ok"
    assert out["plane"] == "/device:TPU:0"  # TPU beats busier host
    assert out["line"] == "XLA Ops"  # per-HLO line, not the module
    assert out["total_ms"] == pytest.approx(40.0)
    assert out["top_ops"][0] == {
        "op": "fusion.1", "ms": 30.0, "pct": 75.0,
    }


def test_xprof_report_degrades_to_json_error(
    tmp_path, monkeypatch, capsys
):
    # no tensorflow in the container: summarize answers with a JSON
    # error record, main() prints it and returns — never a traceback
    monkeypatch.setitem(sys.modules, "tensorflow", None)
    xr = _load_xprof_report()
    out = xr.summarize(str(tmp_path))
    assert out["xprof"] == "unavailable"
    assert "error" in out and out["dir"] == str(tmp_path)
    printed = xr.main([str(tmp_path)])
    assert printed["xprof"] == "unavailable"
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["xprof"] == "unavailable"


def test_xprof_report_empty_dir_reports_no_traces(
    tmp_path, monkeypatch
):
    _install_fake_xplane(monkeypatch)
    xr = _load_xprof_report()
    out = xr.summarize(str(tmp_path))
    assert out["xprof"] == "no .xplane.pb found"
