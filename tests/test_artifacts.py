"""The compiled-program artifact store (serve.artifacts): durable
content-addressed AOT executables and the staged-warmup ordering.

Contracts under test (ISSUE 16):
- serialize/deserialize round-trips a real AOT-compiled executable
  (no retrace, no recompile) and refuses a foreign payload schema;
- a torn or truncated manifest line reads as ABSENT, never as an
  error or a poisoned record — the registry/ledger stance;
- N concurrent publishers of one key: exactly one WINS (O_EXCL
  link), one manifest record, payload intact;
- cross-chip and cross-fingerprint fetches are REFUSED, as is a
  record published under a different jax release (newest record
  wins, so a skewed republish shadows a good one — and is refused);
- a corrupt payload (truncation, hand edit) reads as absent and the
  serving engine falls back to LIVE COMPILE, then republishes — the
  repair path heals the store for the next joiner;
- a second engine on a warm store fetches every bucket program and
  performs ZERO bucket-program XLA compiles, serving bit-identical
  results;
- rank_buckets: declared order wins (typos refused), capture
  frequency next, configured order last.
"""
import json
import os
import pickle
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine
from ccsc_code_iccv2017_tpu.serve import artifacts as arts
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError


def _blob(seed=0, n=2048):
    return np.random.default_rng(seed).bytes(n)


def _store(tmp_path, name="store"):
    return arts.ArtifactStore(str(tmp_path / name))


def _publish(store, key="cpu-single-aaaa", payload=None, **kw):
    kw.setdefault("fingerprint", "aaaa")
    kw.setdefault("chip", "cpu")
    return store.publish(key, payload or _blob(), **kw)


# --------------------------------------------------------------------
# wire format
# --------------------------------------------------------------------


def test_serialize_roundtrip_executes_without_recompile():
    """A deserialized executable is the same program: same bytes out,
    and the load path never enters jax.jit (no trace, no compile)."""

    def f(a, b):
        return a * 2.0 + b

    x = jnp.arange(8.0, dtype=jnp.float32)
    y = jnp.ones((8,), jnp.float32)
    compiled = jax.jit(f).lower(x, y).compile()
    blob = arts.serialize_program(compiled)
    loaded = arts.deserialize_program(blob)
    np.testing.assert_array_equal(
        np.asarray(loaded(x, y)), np.asarray(compiled(x, y))
    )


def test_deserialize_refuses_foreign_payload_schema():
    junk = pickle.dumps((999, b"", None, None))
    with pytest.raises(ValueError, match="payload schema"):
        arts.deserialize_program(junk)


def test_fingerprint_sensitivity():
    """Anything that changes the lowered program changes the
    fingerprint; a fresh computation of the same identity matches."""
    geom = ProblemGeom((3, 3), 4)
    base = dict(bucket=(2, (12, 12)), geom=geom,
                knobs={"arm": "f32"})
    fp = arts.program_fingerprint(**base)
    assert fp == arts.program_fingerprint(**base)
    assert fp != arts.program_fingerprint(
        **dict(base, bucket=(4, (12, 12))))
    assert fp != arts.program_fingerprint(
        **dict(base, knobs={"arm": "bf16"}))
    assert fp != arts.program_fingerprint(
        **dict(base, mesh_shape=(2, 4)))
    key = arts.artifact_key(fp, "cpu")
    assert key != arts.artifact_key(fp, "tpu-v5e")
    assert key != arts.artifact_key(fp, "cpu", (2, 4))


# --------------------------------------------------------------------
# store durability
# --------------------------------------------------------------------


def test_publish_fetch_roundtrip(tmp_path):
    st = _store(tmp_path)
    payload = _blob()
    rec, status = _publish(st, payload=payload, bucket="2@12x12")
    assert status == "won" and rec["key"] == "cpu-single-aaaa"
    got, how = st.fetch(
        "cpu-single-aaaa", fingerprint="aaaa", chip="cpu"
    )
    assert how == "hit" and got == payload
    assert st.keys() == ["cpu-single-aaaa"]
    st.close()


def test_torn_manifest_line_reads_as_absent(tmp_path):
    """A publisher killed mid-append leaves a torn JSONL tail: the
    record it was writing is ABSENT; every earlier record survives."""
    st = _store(tmp_path)
    _publish(st, payload=_blob())
    st.close()
    man = tmp_path / "store" / "manifest.jsonl"
    whole = man.read_bytes()
    # a second record, torn mid-line (no newline, truncated JSON)
    torn = json.dumps({"key": "cpu-single-bbbb", "sha256": "x" * 64})
    man.write_bytes(whole + torn[: len(torn) // 2].encode())
    st2 = _store(tmp_path)
    assert st2.keys() == ["cpu-single-aaaa"]
    assert st2.resolve("cpu-single-bbbb") is None
    assert st2.fetch("cpu-single-bbbb")[1] == "miss"
    # the good record still fetches
    assert st2.fetch("cpu-single-aaaa")[1] == "hit"
    # and the store writes on top of the torn tail without poisoning
    _publish(st2, key="cpu-single-cccc", fingerprint="cccc")
    st2.close()
    st3 = _store(tmp_path)
    assert st3.fetch("cpu-single-cccc")[1] == "hit"
    st3.close()


def test_concurrent_publish_exactly_one_winner(tmp_path):
    st = _store(tmp_path)
    payload = _blob()
    statuses = []
    lock = threading.Lock()
    start = threading.Barrier(8)

    def pub():
        start.wait()
        _rec, status = _publish(st, payload=payload)
        with lock:
            statuses.append(status)

    ts = [threading.Thread(target=pub) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert statuses.count("won") == 1, statuses
    assert all(s in ("won", "lost", "exists") for s in statuses)
    # one manifest record, payload intact, no tmp droppings
    assert len(st._read_manifest()) == 1
    assert st.fetch("cpu-single-aaaa")[0] == payload
    pdir = tmp_path / "store" / "programs"
    assert [p.name for p in pdir.iterdir()] == ["cpu-single-aaaa.bin"]
    st.close()


def test_foreign_artifact_refused(tmp_path):
    """Wrong chip, wrong fingerprint, wrong jax release: all read as
    a miss — a foreign executable must never be loaded."""
    st = _store(tmp_path)
    _publish(st)
    assert st.fetch(
        "cpu-single-aaaa", fingerprint="aaaa", chip="tpu-v5e"
    )[1] == "chip_mismatch"
    assert st.fetch(
        "cpu-single-aaaa", fingerprint="ffff", chip="cpu"
    )[1] == "fingerprint_mismatch"
    # a NEWER record under a skewed jax release shadows the good one
    # (newest wins) and is refused — the caller live-compiles and the
    # republish heals the key
    rec = st.resolve("cpu-single-aaaa")
    skew = dict(rec, jax="0.0.0", seq=rec["seq"] + 1)
    with open(tmp_path / "store" / "manifest.jsonl", "a") as f:
        f.write(json.dumps(skew) + "\n")
    st2 = _store(tmp_path)
    assert st2.fetch(
        "cpu-single-aaaa", fingerprint="aaaa", chip="cpu"
    )[1] == "version_skew"
    st2.close()
    st.close()


def test_missing_and_corrupt_payload_read_as_absent(tmp_path):
    st = _store(tmp_path)
    payload = _blob()
    _publish(st, payload=payload)
    ppath = tmp_path / "store" / "programs" / "cpu-single-aaaa.bin"
    # truncation = corrupt (sha re-verified on every fetch)
    ppath.write_bytes(payload[: len(payload) // 2])
    assert st.fetch("cpu-single-aaaa")[1] == "corrupt"
    os.unlink(ppath)
    assert st.fetch("cpu-single-aaaa")[1] == "missing_payload"
    # repair: republishing the true bytes heals the key
    _rec, status = _publish(st, payload=payload)
    assert status in ("won", "repair")
    assert st.fetch("cpu-single-aaaa") == (payload, "hit")
    st.close()


# --------------------------------------------------------------------
# staged-warmup ordering
# --------------------------------------------------------------------


def test_rank_buckets_declared_order_wins():
    table = [(2, (12, 12)), (4, (16, 16)), (2, (24, 24))]
    order = arts.rank_buckets(table, declared=["2@24x24"])
    assert order == [(2, (24, 24)), (2, (12, 12)), (4, (16, 16))]
    with pytest.raises(CCSCInputError, match="not.*configured"):
        arts.rank_buckets(table, declared=["2@99x99"])
    # no declaration, no capture: configured order stands
    assert arts.rank_buckets(table) == table


# --------------------------------------------------------------------
# engine integration: fetch-instead-of-compile + self-healing
# --------------------------------------------------------------------


def _bank(k=4, s=3, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg():
    return SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_objective=True, track_psnr=True,
    )


def _engine(d, store, mdir, buckets=((2, (12, 12)),)):
    scfg = ServeConfig(
        buckets=buckets, max_wait_ms=2.0, artifact_store=str(store),
        metrics_dir=str(mdir), verbose="none",
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return CodecEngine(d, ReconstructionProblem(geom), _cfg(), scfg)


def _serve_one(eng, seed=1):
    r = np.random.default_rng(seed)
    x = r.random((12, 12)).astype(np.float32)
    m = (r.random((12, 12)) < 0.5).astype(np.float32)
    return eng.submit(x * m, mask=m, x_orig=x).result(timeout=120)


def _bucket_compiles(events):
    return [
        e for e in events
        if e["type"] == "compile" and e.get("kind") == "compile"
        and "ccsc_bucket_program" in (e.get("fun_name") or "")
    ]


def test_warm_store_engine_fetches_and_never_compiles(tmp_path):
    """The elasticity contract end-to-end in one process: engine A
    publishes, engine B fetches — zero bucket-program compiles in
    B's obs stream, bit-identical served bytes."""
    d = _bank()
    store = tmp_path / "store"
    e1 = _engine(d, store, tmp_path / "m1")
    try:
        r1 = _serve_one(e1)
    finally:
        e1.close()
    ev1 = obs.read_events(str(tmp_path / "m1"), recursive=True)
    assert [
        e["status"] for e in ev1 if e["type"] == "artifact_publish"
    ] == ["won"]
    assert len(_bucket_compiles(ev1)) == 1

    e2 = _engine(d, store, tmp_path / "m2")
    try:
        r2 = _serve_one(e2)
    finally:
        e2.close()
    ev2 = obs.read_events(str(tmp_path / "m2"), recursive=True)
    assert [
        e["status"] for e in ev2 if e["type"] == "artifact_fetch"
    ] == ["hit"]
    warm = [e for e in ev2 if e["type"] == "serve_warmup"]
    assert [e["source"] for e in warm] == ["fetched"]
    assert _bucket_compiles(ev2) == []
    ready = [e for e in ev2 if e["type"] == "serve_ready"]
    assert ready[-1]["n_fetched"] == 1
    assert ready[-1]["n_compiled"] == 0
    np.testing.assert_array_equal(
        np.asarray(r1.recon), np.asarray(r2.recon)
    )


def test_corrupt_artifact_falls_back_to_live_compile_and_heals(
    tmp_path,
):
    """A corrupt stored executable must cost availability nothing:
    the joining engine refuses it (sha), live-compiles, REPUBLISHES
    (repair) — and the next joiner fetches clean."""
    d = _bank()
    store = tmp_path / "store"
    e1 = _engine(d, store, tmp_path / "m1")
    e1.close()
    (bin_path,) = (store / "programs").iterdir()
    good = bin_path.read_bytes()
    bin_path.write_bytes(b"garbage" + good[: len(good) // 3])

    e2 = _engine(d, store, tmp_path / "m2")
    try:
        res = _serve_one(e2)
    finally:
        e2.close()
    assert res.recon.shape == (12, 12)
    ev2 = obs.read_events(str(tmp_path / "m2"), recursive=True)
    assert [
        e["status"] for e in ev2 if e["type"] == "artifact_fetch"
    ] == ["corrupt"]
    warm = [e for e in ev2 if e["type"] == "serve_warmup"]
    assert [e["source"] for e in warm] == ["compiled"]
    assert [
        e["status"] for e in ev2 if e["type"] == "artifact_publish"
    ] == ["repair"]

    # healed: the third joiner fetches
    e3 = _engine(d, store, tmp_path / "m3")
    e3.close()
    ev3 = obs.read_events(str(tmp_path / "m3"), recursive=True)
    assert [
        e["status"] for e in ev3 if e["type"] == "artifact_fetch"
    ] == ["hit"]
    assert _bucket_compiles(ev3) == []
