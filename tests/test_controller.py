"""The SLO-feedback capacity controller (serve.controller) — the
fail-safety contracts of ISSUE 17:

- sensor blackout / stale telemetry -> holdoff, NEVER a scale-down
  (fail safe on blind sensors);
- actuator hang -> timeout/retry ladder -> circuit breaker OPEN +
  ``ctrl_holdoff`` + the ``ctrl_breaker_open`` gauge, while the data
  plane never blocks;
- controller crash mid-scale (CCSC_FAULT_CTRL_CRASH_SCALE) -> the
  fleet keeps serving exactly as configured, and a RESTARTED
  controller reconciles from live state (``fleet.replica_target``),
  not from controller memory;
- flap guard: an oscillating load never reaches the ``sustain``
  streak, so the controller holds still;
- hysteresis brownout: the degrade rung engages at ``brownout_frac``
  and releases below ``brownout_exit_frac``;
- bounds reconciliation, at-max/at-min holdoffs, the HBM scale-up
  veto, and coarse-grain host-pool scaling.

Everything here drives a FakeFleet — the controller is strictly
advisory, so its entire contract is visible through the actuator
calls it makes and the ``ctrl_*`` events it emits. The real-fleet
elasticity actuators (``set_replica_count`` grow/shrink, the ceiling
recompute on lifecycle transitions) are covered in test_fleet.py,
and the end-to-end diurnal acceptance in scripts/chaos_smoke.py.

Also here: ``apps.serve.ResubmitBackoff`` — the satellite fix
splitting BucketCold vs Overloaded escalation counters.
"""
import time

import pytest

from ccsc_code_iccv2017_tpu.apps.serve import ResubmitBackoff
from ccsc_code_iccv2017_tpu.config import ControllerConfig
from ccsc_code_iccv2017_tpu.serve.controller import CapacityController
from ccsc_code_iccv2017_tpu.serve.engine import BucketCold
from ccsc_code_iccv2017_tpu.serve.fleet import Overloaded
from ccsc_code_iccv2017_tpu.utils import faults, obs


@pytest.fixture(autouse=True)
def _ctrl_isolation(monkeypatch):
    for v in (
        "CCSC_FAULT_CTRL_SENSOR_BLACKOUT",
        "CCSC_FAULT_CTRL_BLACKOUT_S",
        "CCSC_FAULT_CTRL_ACT_HANG",
        "CCSC_FAULT_CTRL_ACT_HANG_S",
        "CCSC_FAULT_CTRL_CRASH_SCALE",
        "CCSC_FAULT_STATE_DIR",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


class FakeFleet:
    """The controller's entire world: one sensor (control_snapshot)
    and three actuators, each call recorded. Emits through a REAL obs
    run so the ctrl_* event contract is exercised end to end."""

    def __init__(self, run, target=1):
        self._run = run
        self._target = target
        self._brownout = False
        self.scale_calls = []
        self.brownout_calls = []
        self.gauges = {}
        self.fail_snapshot = False
        self.stale_age_s = 0.0
        self.snap = dict(
            queue_depth=0,
            ceiling=10,
            rung=0,
            live_replicas=target,
            abandoned=0,
            bound_rps=5.0,
            warm_replicas=target,
            warmup_eta_s=0.0,
            p99_ms=None,
            slo_p99_target_ms=None,
        )

    @property
    def replica_target(self):
        return self._target

    def control_snapshot(self):
        if self.fail_snapshot:
            raise RuntimeError("sensors down")
        s = dict(self.snap)
        s["t"] = time.time() - self.stale_age_s
        s["replica_target"] = self._target
        s["brownout"] = self._brownout
        return s

    def set_replica_count(self, n, reason="manual"):
        old = self._target
        self._target = n
        self.scale_calls.append((old, n, reason))
        return {"from_n": old, "to_n": n}

    def set_brownout(self, on, reason="controller"):
        self.brownout_calls.append((on, reason))
        changed = on != self._brownout
        self._brownout = on
        return changed

    def set_ctrl_gauge(self, name, value):
        self.gauges[name] = value


def _cfg(**kw):
    base = dict(
        min_replicas=1,
        max_replicas=3,
        interval_s=0.01,
        high_frac=0.8,
        low_frac=0.2,
        sustain=2,
        cooldown_s=0.05,
        stale_s=5.0,
        act_timeout_s=0.25,
        act_retries=0,
        act_backoff_s=0.01,
        breaker_after=2,
        breaker_reset_s=0.5,
        # out of the way unless a test targets brownout
        brownout_frac=1.4,
        brownout_exit_frac=0.05,
        hbm_limit_mb=0.0,
    )
    base.update(kw)
    return ControllerConfig(**base)


@pytest.fixture
def run(tmp_path):
    r = obs.start_run(
        str(tmp_path), algorithm="ctrl_test", verbose="none"
    )
    yield r
    if not r.closed:
        r.close(status="ok")


def _events(tmp_path, type_=None):
    ev = obs.read_events(str(tmp_path))
    return [e for e in ev if type_ is None or e["type"] == type_]


# -- the happy control loop ----------------------------------------------


def test_scale_up_on_sustained_pressure(run, tmp_path):
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(fleet, _cfg())
    fleet.snap["queue_depth"] = 9  # frac 0.9 >= high_frac
    ctrl.step()
    assert fleet.scale_calls == []  # one tick is not sustained
    ctrl.step()
    assert fleet.scale_calls == [(1, 2, "controller:queue_pressure")]
    decs = _events(tmp_path, "ctrl_decision")
    assert decs and decs[-1]["action"] == "scale_up"
    assert decs[-1]["snapshot"]["queue_depth"] == 9
    scales = _events(tmp_path, "ctrl_scale")
    assert scales[-1]["direction"] == "up"
    assert scales[-1]["ok"] is True
    assert (scales[-1]["from_n"], scales[-1]["to_n"]) == (1, 2)


def test_scale_up_on_slo_breach(run, tmp_path):
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(fleet, _cfg())
    fleet.snap.update(
        queue_depth=1, p99_ms=250.0, slo_p99_target_ms=100.0
    )
    ctrl.step()
    ctrl.step()
    assert fleet.scale_calls == [(1, 2, "controller:slo_breach")]


def test_scale_down_needs_green_everything(run, tmp_path):
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(fleet, _cfg())
    # idle queue but the overload ladder is not at rung 0: hold
    fleet.snap.update(queue_depth=0, rung=1)
    for _ in range(5):
        ctrl.step()
    assert fleet.scale_calls == []
    # ladder green now -> drain down after the sustain streak
    fleet.snap["rung"] = 0
    ctrl.step()
    ctrl.step()
    assert fleet.scale_calls == [(2, 1, "controller:idle_capacity")]


def test_cooldown_suppresses_back_to_back_scaling(run, tmp_path):
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(fleet, _cfg(cooldown_s=30.0))
    fleet.snap["queue_depth"] = 9
    for _ in range(6):
        ctrl.step()
    # one scale, then the cooldown holds even under live pressure
    assert fleet.scale_calls == [(1, 2, "controller:queue_pressure")]
    holds = _events(tmp_path, "ctrl_holdoff")
    assert any(h["reason"] == "cooldown:scale_up" for h in holds)


def test_flap_guard_oscillating_load(run, tmp_path):
    """A load oscillating between the bands every tick never builds a
    ``sustain`` streak — the controller must hold perfectly still."""
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(fleet, _cfg(sustain=3))
    for i in range(18):
        fleet.snap["queue_depth"] = 9 if i % 2 == 0 else 0
        ctrl.step()
    assert fleet.scale_calls == []
    assert fleet.brownout_calls == []
    assert _events(tmp_path, "ctrl_scale") == []


def test_bounds_holdoffs_and_reconcile(run, tmp_path):
    fleet = FakeFleet(run, target=3)
    ctrl = CapacityController(fleet, _cfg())
    fleet.snap["queue_depth"] = 10  # pressure at max_replicas
    for _ in range(3):
        ctrl.step()
    assert fleet.scale_calls == []
    holds = _events(tmp_path, "ctrl_holdoff")
    assert any(h["reason"] == "at_max_replicas" for h in holds)
    # a fleet below the configured floor is corrected immediately
    # (reconciliation, no streak needed)
    fleet2 = FakeFleet(run, target=1)
    ctrl2 = CapacityController(fleet2, _cfg(min_replicas=2))
    fleet2.snap["queue_depth"] = 5  # mid-band: no pressure either way
    ctrl2.step()
    assert fleet2.scale_calls == [
        (1, 2, "controller:reconcile_bounds")
    ]


# -- fail-safe sensors ---------------------------------------------------


def test_sensor_blackout_holds_and_never_scales_down(
    run, tmp_path, monkeypatch
):
    """Chaos: CCSC_FAULT_CTRL_SENSOR_BLACKOUT blinds the sensor read.
    The fleet is idle (scale-down would be wanted with live
    telemetry) — the controller must emit ctrl_holdoff and hold."""
    monkeypatch.setenv("CCSC_FAULT_CTRL_SENSOR_BLACKOUT", "1")
    monkeypatch.setenv("CCSC_FAULT_CTRL_BLACKOUT_S", "60")
    faults.reset()
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(fleet, _cfg(sustain=1))
    fleet.snap["queue_depth"] = 0  # down pressure, if it could see
    for _ in range(6):
        ctrl.step()
    assert fleet.scale_calls == []
    assert fleet.brownout_calls == []
    holds = _events(tmp_path, "ctrl_holdoff")
    assert holds and all(
        h["reason"] == "sensor_stale" for h in holds
    )
    assert any(
        e["fault"] == "ctrl_blackout"
        for e in _events(tmp_path, "fault_fired")
    )


def test_stale_snapshot_fails_safe(run, tmp_path):
    """Telemetry older than stale_s is as blind as no telemetry."""
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(fleet, _cfg(sustain=2, stale_s=1.0))
    fleet.snap["queue_depth"] = 0
    fleet.stale_age_s = 30.0
    for _ in range(4):
        ctrl.step()
    assert fleet.scale_calls == []
    assert any(
        h["reason"] == "sensor_stale"
        for h in _events(tmp_path, "ctrl_holdoff")
    )
    # sensors return: pressure must RE-sustain from zero (streaks
    # were reset) before anything moves
    fleet.stale_age_s = 0.0
    ctrl.step()
    assert fleet.scale_calls == []
    ctrl.step()
    assert fleet.scale_calls == [(2, 1, "controller:idle_capacity")]


def test_snapshot_exception_fails_safe(run, tmp_path):
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(fleet, _cfg(sustain=1))
    fleet.snap["queue_depth"] = 0
    fleet.fail_snapshot = True
    for _ in range(3):
        ctrl.step()
    assert fleet.scale_calls == []
    assert any(
        h["reason"] == "sensor_stale"
        for h in _events(tmp_path, "ctrl_holdoff")
    )


# -- stuck actuators -----------------------------------------------------


def test_actuator_hang_opens_breaker(run, tmp_path, monkeypatch):
    """Chaos: every actuator invocation wedges (the hang count spans
    the whole retry budget). The timeout ladder must fail each
    invocation, the breaker must OPEN after breaker_after exhausted
    invocations, further attempts are refused with ctrl_holdoff, and
    the ctrl_breaker_open gauge goes to 1 — while the data plane
    (here: the recorded actuator calls) never completed a scale."""
    monkeypatch.setenv("CCSC_FAULT_CTRL_ACT_HANG", "10")
    monkeypatch.setenv("CCSC_FAULT_CTRL_ACT_HANG_S", "3600")
    faults.reset()
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(
        fleet,
        _cfg(sustain=1, act_timeout_s=0.1, cooldown_s=0.0001),
    )
    fleet.snap["queue_depth"] = 9
    ctrl.step()  # invocation 1: hangs -> timeout -> failed
    ctrl.step()  # invocation 2: hangs -> breaker opens
    ctrl.step()  # refused at the breaker, no invocation
    assert fleet.scale_calls == []  # the hung fn never ran to completion
    assert fleet.gauges.get("ctrl_breaker_open") == 1.0
    scales = _events(tmp_path, "ctrl_scale")
    assert scales and all(s["ok"] is False for s in scales)
    assert any(
        h["reason"] == "breaker_open:scale_up"
        for h in _events(tmp_path, "ctrl_holdoff")
    )
    assert any(
        e["fault"] == "ctrl_act_hang"
        for e in _events(tmp_path, "fault_fired")
    )


def test_breaker_half_opens_after_reset(run, tmp_path, monkeypatch):
    monkeypatch.setenv("CCSC_FAULT_CTRL_ACT_HANG", "2")
    faults.reset()
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(
        fleet,
        _cfg(
            sustain=1, act_timeout_s=0.1, cooldown_s=0.0001,
            breaker_reset_s=0.2,
        ),
    )
    fleet.snap["queue_depth"] = 9
    ctrl.step()
    ctrl.step()  # breaker open now, both hang charges spent
    assert fleet.gauges.get("ctrl_breaker_open") == 1.0
    time.sleep(0.25)  # past breaker_reset_s: half-open probe allowed
    ctrl.step()
    assert fleet.scale_calls == [(1, 2, "controller:queue_pressure")]
    assert fleet.gauges.get("ctrl_breaker_open") == 0.0


# -- controller death ----------------------------------------------------


def test_crash_mid_scale_leaves_fleet_as_configured(
    run, tmp_path, monkeypatch
):
    """Chaos: the controller dies BETWEEN committing to a scale
    decision and invoking the actuator. Hard invariant: the fleet's
    configuration is untouched. A restarted controller then
    reconciles from fleet.replica_target and completes the scale."""
    monkeypatch.setenv("CCSC_FAULT_CTRL_CRASH_SCALE", "1")
    faults.reset()
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(fleet, _cfg(sustain=1)).start()
    fleet.snap["queue_depth"] = 9
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not ctrl.died:
        time.sleep(0.01)
    assert ctrl.died  # the loop thread is gone
    assert not ctrl.alive
    # the fleet serves exactly as configured: no actuation happened
    assert fleet.scale_calls == []
    assert fleet.replica_target == 1
    # the decision WAS committed (emitted) before the crash — the
    # stream shows intent, the fleet shows no mutation
    assert _events(tmp_path, "ctrl_decision")
    ctrl.close()

    # restart: a fresh controller holds no memory of the dead one —
    # it re-reads live state and the still-live pressure re-sustains
    ctrl2 = CapacityController(fleet, _cfg(sustain=1))
    ctrl2.step()
    assert fleet.scale_calls == [(1, 2, "controller:queue_pressure")]
    assert fleet.replica_target == 2
    ctrl2.close()


def test_close_is_advisory(run, tmp_path):
    fleet = FakeFleet(run, target=2)
    fleet.snap["queue_depth"] = 5  # mid-band: no pressure either way
    ctrl = CapacityController(fleet, _cfg()).start()
    assert ctrl.alive
    ctrl.close()
    assert not ctrl.alive
    assert fleet.scale_calls == []
    assert fleet.replica_target == 2


# -- brownout ------------------------------------------------------------


def test_brownout_hysteresis(run, tmp_path):
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(
        fleet,
        _cfg(
            brownout_frac=0.9, brownout_exit_frac=0.3,
            cooldown_s=0.01, high_frac=1.45, sustain=50,
        ),
    )
    fleet.snap["queue_depth"] = 9  # frac 0.9: engage
    ctrl.step()
    assert fleet.brownout_calls == [(True, "controller")]
    bo = _events(tmp_path, "ctrl_brownout")
    assert bo[-1]["on"] is True
    # inside the band (0.3 < 0.5 < 0.9): no release, no re-engage
    fleet.snap["queue_depth"] = 5
    time.sleep(0.02)
    ctrl.step()
    assert len(fleet.brownout_calls) == 1
    # below the exit: release
    fleet.snap["queue_depth"] = 2
    time.sleep(0.02)
    ctrl.step()
    assert fleet.brownout_calls[-1] == (False, "controller")
    bo = _events(tmp_path, "ctrl_brownout")
    assert bo[-1]["on"] is False


# -- scale-up vetos ------------------------------------------------------


class FakeMemWatch:
    def __init__(self, peak_mb):
        self._peak = int(peak_mb * 2**20)

    def sample(self):
        return self._peak

    @property
    def peak_bytes(self):
        return self._peak


def test_hbm_watermark_vetoes_scale_up(run, tmp_path):
    fleet = FakeFleet(run, target=1)
    ctrl = CapacityController(
        fleet,
        _cfg(sustain=1, hbm_limit_mb=100.0),
        memwatch=FakeMemWatch(peak_mb=200.0),
    )
    fleet.snap["queue_depth"] = 9
    for _ in range(3):
        ctrl.step()
    assert fleet.scale_calls == []
    assert any(
        h["reason"] == "hbm_watermark"
        for h in _events(tmp_path, "ctrl_holdoff")
    )


# -- coarse-grain host scaling -------------------------------------------


class FakePool:
    def __init__(self, n=1):
        self.n_hosts = n
        self.calls = []

    def grow(self):
        self.n_hosts += 1
        self.calls.append("grow")
        return f"host-{self.n_hosts}"

    def shrink(self):
        self.n_hosts -= 1
        self.calls.append("shrink")
        return f"host-{self.n_hosts + 1}"


def test_host_pool_scales_when_replicas_pinned(run, tmp_path):
    pool = FakePool(n=1)
    fleet = FakeFleet(run, target=2)
    ctrl = CapacityController(
        fleet,
        _cfg(
            min_replicas=2, max_replicas=2, sustain=1,
            min_hosts=1, max_hosts=2, cooldown_s=0.0001,
        ),
        host_pool=pool,
    )
    fleet.snap["queue_depth"] = 9  # replicas pinned -> host axis
    ctrl.step()
    assert pool.calls == ["grow"]
    scales = _events(tmp_path, "ctrl_scale")
    assert scales[-1]["direction"] == "host_up"
    assert (scales[-1]["from_n"], scales[-1]["to_n"]) == (1, 2)
    # trough: replicas already at min -> hosts shrink back to floor
    fleet.snap["queue_depth"] = 0
    time.sleep(0.01)
    ctrl.step()
    assert pool.calls == ["grow", "shrink"]
    assert pool.n_hosts == 1


# -- the resubmit backoff split (apps.serve satellite) -------------------


def test_resubmit_backoff_tracks_classes_separately():
    """Interleaved BucketCold and Overloaded refusals escalate on
    SEPARATE counters: a cold bucket during scale-up must not
    inflate the overload backoff (the pre-fix single counter gave
    the 5th interleaved refusal a 16x multiplier; split counters
    give each class its own doubling)."""
    bo = ResubmitBackoff()
    cold = BucketCold("64x64", 1.0)
    over = Overloaded("queue full", 1.0)
    assert bo.delay_for(over) == 1.0
    assert bo.delay_for(cold) == 1.0  # NOT 2.0: its own counter
    assert bo.delay_for(over) == 2.0
    assert bo.delay_for(cold) == 2.0
    assert bo.delay_for(over) == 4.0
    assert bo.delay_for(cold) == 4.0
    assert bo.consec("Overloaded") == 3
    assert bo.consec("BucketCold") == 3
    # an admitted request clears all escalation
    bo.reset()
    assert bo.delay_for(over) == 1.0
    assert bo.delay_for(cold) == 1.0


def test_resubmit_backoff_caps():
    bo = ResubmitBackoff()
    over = Overloaded("queue full", 3.0)
    delays = [bo.delay_for(over) for _ in range(10)]
    assert delays[0] == 3.0
    assert max(delays) == ResubmitBackoff.CAP_S
    assert delays[-1] == ResubmitBackoff.CAP_S
    # the hint itself is honored under the cap
    cold = BucketCold("64x64", 0.25)
    assert bo.delay_for(cold) == 0.25


def test_refusal_trio_deadline_is_terminal():
    """The refusal trio routes DISTINCTLY in the resubmit loop:
    Overloaded and BucketCold are retryable (each on its own
    escalation counter), DeadlineExceeded is terminal — it is not a
    subclass of either retryable refusal (so it can never match
    their except clause) and it deliberately carries NO
    ``retry_after_s`` hint: an expired budget cannot be fixed by
    waiting, so the backoff machinery must have nothing to honor."""
    from ccsc_code_iccv2017_tpu.serve.engine import DeadlineExceeded

    dead = DeadlineExceeded("admission", 123.0)
    assert not isinstance(dead, (Overloaded, BucketCold))
    assert not hasattr(dead, "retry_after_s")
    assert dead.where == "admission"
    assert dead.deadline == 123.0
    bo = ResubmitBackoff()
    with pytest.raises(AttributeError):
        bo.delay_for(dead)  # never reaches the retry path
    # the terminal refusal leaves the retryable counters untouched
    assert bo.consec("Overloaded") == 0
    assert bo.consec("BucketCold") == 0
