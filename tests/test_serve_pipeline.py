"""Pipelined dispatch (ISSUE 20): the engine worker holds up to
``ServeConfig.pipeline_depth`` launched batches in flight, overlapping
batch N+1's host->device upload with batch N's solve.

Contracts under test:

- BIT-IDENTITY: a depth-2 engine's results on a heterogeneous stream
  (multiple buckets, padded requests mixed in) are BITWISE a depth-1
  engine's — recon, objective/PSNR traces, stopping iteration. The
  overlap changes WHEN a batch is uploaded, never what the program
  computes, so this holds exactly (same AOT programs, same batches).
- LEDGER IDENTITY: only a non-default depth keys the knob dict
  ("pipeline": depth) — depth-1 engines keep their historical knob
  digest bit-for-bit, and the bench's pipelined arm accrues its OWN
  perf-ledger configuration (third row), judged by the same gate.
- RESOLUTION: ServeConfig.pipeline_depth wins; None falls back to
  CCSC_SERVE_PIPELINE; invalid depths are refused at config time.
- FAULTS: a replica killed mid-stream with a prefetched batch in
  flight loses nothing — the fleet redelivers exactly once and the
  results stay bit-identical (the in-flight lane is just work the
  casualty never acked).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet
from ccsc_code_iccv2017_tpu.utils import faults, obs


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    for v in (
        "CCSC_SERVE_PIPELINE",
        "CCSC_SERVE_MESH",
        "CCSC_FAULT_ENGINE_KILL_REQ",
        "CCSC_FAULT_ENGINE_KILL_REPLICA",
        "CCSC_WATCHDOG_MIN_S",
        "CCSC_WATCHDOG_COMPILE_S",
        "CCSC_PERF_LEDGER",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


def _bank(k=6, s=5, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=6, tol=1e-4,
        verbose="none", track_objective=True, track_psnr=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _req(size, seed=1, keep=0.5):
    r = np.random.default_rng(seed)
    x = r.random((size, size)).astype(np.float32)
    m = (r.random((size, size)) < keep).astype(np.float32)
    return x, m


def _engine(d, cfg, buckets, tmp_path=None, **kw):
    scfg = ServeConfig(
        buckets=buckets,
        max_wait_ms=kw.pop("max_wait_ms", 5.0),
        metrics_dir=str(tmp_path) if tmp_path is not None else None,
        verbose="none",
        mesh_shape=kw.pop("mesh_shape", ()),
        **kw,
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)


def _serve_all(eng, reqs):
    futs = [eng.submit(x * m, mask=m, x_orig=x) for x, m in reqs]
    return [f.result(timeout=300) for f in futs]


# -------------------------------------------------------- bit parity


def test_depth2_bit_identical_to_depth1_hetero_stream():
    """The tentpole parity contract on a heterogeneous stream: two
    buckets, off-bucket (padded) sizes mixed in, enough requests that
    the depth-2 worker actually holds a second batch in flight."""
    d = _bank()
    cfg = _cfg()
    buckets = ((2, (16, 16)), (2, (24, 24)))
    sizes = [16, 24, 12, 24, 16, 20, 24, 16, 12, 20, 24, 16]
    reqs = [_req(sz, seed=300 + i) for i, sz in enumerate(sizes)]

    ref_eng = _engine(d, cfg, buckets, pipeline_depth=1)
    try:
        ref = _serve_all(ref_eng, reqs)
    finally:
        ref_eng.close()

    pipe_eng = _engine(d, cfg, buckets, pipeline_depth=2)
    try:
        out = _serve_all(pipe_eng, reqs)
    finally:
        pipe_eng.close()

    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a.recon, b.recon)
        np.testing.assert_array_equal(
            np.asarray(a.trace.obj_vals), np.asarray(b.trace.obj_vals)
        )
        np.testing.assert_array_equal(
            np.asarray(a.trace.psnr_vals),
            np.asarray(b.trace.psnr_vals),
        )
        assert int(a.trace.num_iters) == int(b.trace.num_iters)


# ------------------------------------------- knob identity/resolution


def _ready_knobs(tmp_path, **kw):
    d = _bank(k=4, s=3)
    eng = _engine(
        d, _cfg(max_it=2, tol=0.0, track_psnr=False),
        ((2, (12, 12)),), tmp_path, **kw,
    )
    eng.close()
    ready = [
        e for e in obs.read_events(str(tmp_path))
        if e.get("type") == "serve_ready"
    ]
    assert ready
    return ready[-1]["knobs"]


def test_depth1_keeps_historical_knob_digest(tmp_path):
    knobs = _ready_knobs(tmp_path, pipeline_depth=1)
    assert "pipeline" not in knobs


def test_nondefault_depth_keys_knob_dict(tmp_path):
    knobs = _ready_knobs(tmp_path, pipeline_depth=3)
    assert knobs["pipeline"] == 3


def test_env_fallback_and_config_priority(tmp_path, monkeypatch):
    monkeypatch.setenv("CCSC_SERVE_PIPELINE", "2")
    knobs = _ready_knobs(tmp_path / "env", pipeline_depth=None)
    assert knobs["pipeline"] == 2
    # an explicit config depth wins over the env
    knobs = _ready_knobs(tmp_path / "cfg", pipeline_depth=1)
    assert "pipeline" not in knobs


def test_invalid_depth_refused():
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServeConfig(buckets=((2, (12, 12)),), pipeline_depth=0)


# ------------------------------------------------------------- faults


def test_fleet_kill_with_prefetched_batch_zero_lost(
    tmp_path, monkeypatch,
):
    """Kill a pipelined replica on its first taken request: the
    in-flight lane (a launched-but-unacked second batch) is redelivered
    by the fleet exactly once, bit-identical to an unfaulted engine."""
    monkeypatch.setenv("CCSC_SERVE_PIPELINE", "2")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REPLICA", "0")
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "0.4")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "0.4")
    faults.reset()
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=4, tol=0.0, track_psnr=False)
    buckets = ((4, (12, 12)),)
    reqs = [_req(12, seed=400 + i) for i in range(10)]

    geom = ProblemGeom(d.shape[1:], d.shape[0])
    ref_eng = CodecEngine(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(
            buckets=buckets, max_wait_ms=2.0, verbose="none",
            pipeline_depth=1,
        ),
    )
    try:
        futs = [ref_eng.submit(x * m, mask=m) for x, m in reqs]
        ref = [f.result(timeout=180) for f in futs]
    finally:
        ref_eng.close()

    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(buckets=buckets, max_wait_ms=2.0, verbose="none"),
        FleetConfig(
            replicas=2, min_queue_depth=64, restart_backoff_s=0.05,
            heartbeat_s=0.2, health_interval_s=0.05, verbose="none",
            metrics_dir=str(tmp_path),
        ),
    )
    try:
        futs = [
            fleet.submit(x * m, mask=m, key=f"p{i}")
            for i, (x, m) in enumerate(reqs)
        ]
        res = [f.result(timeout=300) for f in futs]
        assert len(res) == 10
        for i in range(10):
            np.testing.assert_array_equal(res[i].recon, ref[i].recon)
            assert int(res[i].trace.num_iters) == int(
                ref[i].trace.num_iters
            )
    finally:
        fleet.close()

    events = obs.read_events(str(tmp_path), recursive=True)
    dead = [e for e in events if e["type"] == "fleet_replica_dead"]
    assert any(e["replica_id"] == 0 for e in dead)
    served = [
        e["key"] for e in events if e["type"] == "fleet_request"
    ]
    assert sorted(served) == sorted(f"p{i}" for i in range(10))


# ----------------------------------------------------- ledger + gate


def test_pipeline_record_is_its_own_ledger_configuration(
    tmp_path, monkeypatch,
):
    """append_serve_record with a pipelined arm writes a THIRD-row
    class of its own: default + pipeline knob digests stay distinct,
    each accrues history, and an injected 0.5x pipelined record is a
    regression against the pipeline key's band (perf_gate exit-1)."""
    from ccsc_code_iccv2017_tpu.analysis import ledger

    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("CCSC_PERF_LEDGER", path)
    base = {
        "chip": "cpu",
        "shape_key": "solve2d:k32:s7x7:sz64x64",
        "knobs": {"requests": 16, "slots": 4},
        "n_compiles": 3,
        "pipeline_depth": 2,
    }
    for v_def, v_pipe in ((2.0, 2.6), (2.05, 2.62), (1.98, 2.58)):
        rec = dict(
            base,
            engine_requests_per_sec=v_def,
            pipeline_requests_per_sec=v_pipe,
        )
        assert ledger.append_serve_record(rec) is not None
    rows = ledger.Ledger(path).read()
    assert len(rows) == 6
    keys = {ledger.record_key(r) for r in rows}
    assert len(keys) == 2  # default + pipeline configurations
    pipe_rows = [
        r for r in rows if (r.get("knobs") or {}).get("pipeline") == 2
    ]
    assert len(pipe_rows) == 3
    assert all(r["value"] > 2.5 for r in pipe_rows)
    # gate: an injected 0.5x record under the PIPELINE key regresses
    led = ledger.Ledger(path)
    bad = ledger.normalize_record(
        chip="cpu", kind="serve", workload="serve2d",
        shape_key=base["shape_key"],
        knobs=dict(base["knobs"], pipeline=2),
        value=1.3, unit="requests/sec",
    )
    verdicts = ledger.gate(led, record=bad)
    assert any(not v["ok"] for v in verdicts), verdicts
    # ...and a value inside the band passes
    good = dict(bad, value=2.61)
    assert all(v["ok"] for v in ledger.gate(led, record=good))
