"""MATLAB-anchored golden trajectory for the POISSON DECONV SOLVER.

Fifth anchor in the series: a LITERAL, line-ordered float64 NumPy
transcription of 2D/Poisson_deconv/admm_solve_conv_poisson.m — the
reconstruction solver with the most distinctive mechanics (appended
dirac channel :4-7, per-channel sparsity exemption :84, gradient
regularization inside the z-solve :165-176, exact Poisson prox
:193-205, final non-negativity clamp :131, gamma heuristic
20*lambda/max(b) with ratio 5 :34-35).

The reference text contains TWO local deviations from its own intent,
both parameterized here so each can be anchored AND quantified:

1. APPROXIMATE SOLVE (``exact_solve``): solve_conv_term :185-186
   inverts (diag(rho + TG) + conj(d) d^T) with a per-output-channel
   scalar denominator ``rho + TG_k + sum_j |d_j|^2`` — the exact
   Sherman-Morrison denominator ``1 + sum_j |d_j|^2/(rho + TG_j)`` is
   channel-independent, so the formula is exact ONLY where TG = 0
   (the inpainting solver's case). The framework solves the system
   exactly (ops/freq_solvers.py docstring, "DESIGN DIVERGENCE");
   ``exact_solve=True`` replaces :185-186 with a per-frequency
   ``np.linalg.solve`` of the same system, which is what the
   framework must match.

2. DIRAC CHANNEL INDEX (``literal_channel1``): the :4 comment says
   "First one is dirac" and the sparsity exemption :84 and gradient
   regularizer :175 both index CHANNEL 1 — but :7 ``cat(3, kmat,
   k_dirac)`` appends the dirac LAST, so the literal text exempts and
   regularizes a real learned filter while sparsifying the dirac.
   The sibling video-deblur solver prepends
   (admm_solve_video_weighted_sampling.m:5-7), confirming the intent.
   The framework builds to intent (`dirac='append'` exempts the
   appended channel); ``literal_channel1=True`` reproduces the
   text's misindexing so its cost can be measured.

test_poisson_matches_matlab_exact_variant anchors the framework
against the transcription with both deviations resolved to intent
(everything else — update order :78-98, prox formulas, psf2otf
layout :143-156, objective :207-217, clamp :131 — is the literal
text). The two quantification tests pin that each deviation is REAL
(trajectories move apart) without anchoring to it.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)


def fft2(x):
    return np.fft.fft2(x, axes=(0, 1))


def ifft2(x):
    return np.fft.ifft2(x, axes=(0, 1))


def psf2otf(psf, size_x):
    """MATLAB psf2otf: zero-pad to size_x, circularly shift the PSF
    center to index (1,1), fft2 (used at :149, :169-170)."""
    full = np.zeros(size_x)
    full[: psf.shape[0], : psf.shape[1]] = psf
    full = np.roll(
        full, (-(psf.shape[0] // 2), -(psf.shape[1] // 2)), (0, 1)
    )
    return fft2(full)


def prox_sparse(u, theta):
    """ProxSparse = max(0, 1 - theta/|u|) .* u (:30)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
    return np.maximum(0.0, f) * u


def prox_data_masked(u, theta, MtM, Mtb):
    """prox_data_masked (:193-205): exact Poisson prox on observed
    pixels, identity elsewhere."""
    mask = MtM > 0
    pD = 0.5 * (
        u - theta + np.sqrt((u - theta) ** 2 + 4.0 * theta * Mtb)
    )  # :200
    return np.where(mask, pD, u)  # :203


def matlab_poisson_solver(
    b,
    kmat,
    mask,
    lam_res,
    lam_pri,
    max_it,
    exact_solve=False,
    literal_channel1=True,
):
    """Transcription of admm_solve_conv_poisson.m. b, mask: [H, W]
    (the driver codes one image at a time, CreateImagesList —
    reconstruct_poisson_noise.m:15,93); kmat: [s, s, K]. Returns
    (obj_vals [max_it + 1], final clamped reconstruction)."""
    s = kmat.shape[0]
    # :5-7 — dirac appended LAST (the :4 comment notwithstanding)
    k_dirac = np.zeros((s, s))
    k_dirac[s // 2, s // 2] = 1.0  # floor(s/2)+1 in 1-based
    kmat = np.concatenate([kmat, k_dirac[:, :, None]], axis=2)
    K = kmat.shape[2]
    reg_ch = 0 if literal_channel1 else K - 1  # :84/:175 vs intent

    psf_radius = s // 2  # :10
    size_x = (b.shape[0] + 2 * psf_radius, b.shape[1] + 2 * psf_radius)
    ss = size_x[0] * size_x[1]

    # precompute_H_hat (:143-156)
    dhat = np.stack(
        [psf2otf(kmat[:, :, w], size_x) for w in range(K)], axis=2
    )  # :147-150
    dhat_flat = np.reshape(dhat, (ss, K), order="F")  # :153
    dhatTdhat = np.sum(np.conj(dhat_flat) * dhat_flat, axis=1)  # :154
    dhatT = np.conj(dhat_flat.T)  # [K, ss] (:13)

    # precompute_MProx (:135-141)
    MtM = np.zeros(size_x)
    MtM[
        psf_radius : psf_radius + b.shape[0],
        psf_radius : psf_radius + b.shape[1],
    ] = mask  # :137-138 padarray
    Mtb = np.zeros(size_x)
    Mtb[
        psf_radius : psf_radius + b.shape[0],
        psf_radius : psf_radius + b.shape[1],
    ] = b
    Mtb = Mtb * MtM  # :139

    lam = (lam_res, lam_pri)  # :33
    gamma_heuristic = 20.0 * lam_pri / np.max(b)  # :34
    gamma = (gamma_heuristic / 5.0, gamma_heuristic)  # :35

    # solve_conv_term's gradient-regularizer spectra (:165-176)
    Hx = psf2otf(np.array([[1.0, -1.0]]), size_x)  # dy = [1,-1] :166,169
    Hy = psf2otf(np.array([[1.0], [-1.0]]), size_x)  # dx = [1;-1] :167,170
    lambda_smooth = 0.5  # :174
    TG = np.zeros((K, ss))
    TG[reg_ch] = lambda_smooth * np.reshape(
        np.abs(Hx) ** 2 + np.abs(Hy) ** 2, ss, order="F"
    )  # :175-176
    rho = gamma[1] / gamma[0]  # :179

    def solve_conv_term(xi1_hat, xi2_hat):
        """solve_conv_term (:158-191) in its [K, ss] layout; or the
        exact per-frequency solve of the SAME system (deviation 1)."""
        bb = dhatT * np.reshape(xi1_hat, (1, ss), order="F") + (
            rho * np.reshape(xi2_hat, (ss, K), order="F").T
        )  # :182
        if exact_solve:
            x = np.empty_like(bb)
            for f in range(ss):
                A = np.diag(rho + TG[:, f]) + np.outer(
                    np.conj(dhat_flat[f]), dhat_flat[f]
                )
                x[:, f] = np.linalg.solve(A, bb[:, f])
        else:
            scInverse = 1.0 / ((rho + TG) + dhatTdhat[None, :])  # :185
            x = bb / (rho + TG) - (
                1.0
                / (rho + TG)
                * scInverse
                * dhatT
                * np.sum(np.conj(dhatT) * bb, axis=0, keepdims=True)
            )  # :186
        return np.reshape(x.T, (*size_x, K), order="F")  # :189

    def objective(zc):
        """objectiveFunction (:207-217)."""
        Dz = np.real(ifft2(np.sum(dhat * fft2(zc), axis=2)))  # :210
        crop = Dz[
            psf_radius : size_x[0] - psf_radius,
            psf_radius : size_x[1] - psf_radius,
        ]
        f_z = lam_res * 0.5 * np.sum((mask * crop - mask * b) ** 2)  # :211
        g_z = lam_pri * np.sum(np.abs(zc))  # :212
        return f_z + g_z

    # init (:38-48): everything zero
    size_z = (*size_x, K)
    d1 = np.zeros(size_x)
    d2 = np.zeros(size_z)
    z = np.zeros(size_z)
    z_hat = np.zeros(size_z, complex)

    obj_vals = [objective(z)]  # :63
    for _ in range(max_it):  # :75
        v1 = np.real(ifft2(np.sum(dhat * z_hat, axis=2)))  # :78
        v2 = z  # :79
        u1 = prox_data_masked(v1 - d1, lam[0] / gamma[0], MtM, Mtb)  # :82
        u2 = prox_sparse(v2 - d2, lam[1] / gamma[1])  # :83
        u2[:, :, reg_ch] = v2[:, :, reg_ch] - d2[:, :, reg_ch]  # :84
        d1 = d1 - (v1 - u1)  # :88
        d2 = d2 - (z - u2)
        xi1_hat = fft2(u1 + d1)  # :91-92
        xi2_hat = fft2(u2 + d2)
        z_hat = solve_conv_term(xi1_hat, xi2_hat)  # :97
        z = np.real(ifft2(z_hat))  # :98
        obj_vals.append(objective(z))  # :115

    Dz = np.real(ifft2(np.sum(dhat * z_hat, axis=2)))  # :129
    res = Dz[
        psf_radius : size_x[0] - psf_radius,
        psf_radius : size_x[1] - psf_radius,
    ]  # :130
    res = np.maximum(res, 0.0)  # :131 res(res < 0) = 0
    return np.array(obj_vals), res


def _problem(seed=77, H=8, s=3, K=3):
    rng = np.random.default_rng(seed)
    b = rng.poisson(40.0, (H, H)).astype(np.float64)
    b[0, 0] = 60.0  # pin max(b) away from ties for the gamma heuristic
    d = rng.normal(size=(s, s, K))
    d /= np.sqrt(np.sum(d**2, axis=(0, 1), keepdims=True))
    mask = np.ones((H, H))
    return b, d, mask


def test_poisson_matches_matlab_exact_variant():
    """Framework vs the transcription with both text deviations
    resolved to intent (exact solve, dirac channel exempted):
    objective trajectory and final clamped reconstruction must match
    to float32 tolerance. Anchors the Poisson prox, the gamma
    heuristic, the gradient-regularized z-solve system, the update
    order, and the psf2otf layout against the MATLAB text."""
    b, d, mask = _problem()
    n_iters = 5
    ml_objs, ml_res = matlab_poisson_solver(
        b, d, mask, 20.0, 1.0, n_iters,
        exact_solve=True, literal_channel1=False,
    )
    geom = ProblemGeom((3, 3), 3)
    prob = ReconstructionProblem(
        geom,
        data_term="poisson",
        dirac="append",
        grad_reg_dirac=True,
        sparsify_dirac=False,
        clamp_nonneg=True,
    )
    cfg = SolveConfig(
        lambda_residual=20.0,
        lambda_prior=1.0,
        max_it=n_iters,
        tol=0.0,
        gamma_factor=20.0,
        gamma_ratio=5.0,
        lambda_smooth=0.5,
        verbose="none",
        track_objective=True,
    )
    res = reconstruct(
        jnp.asarray(b[None], jnp.float32),
        jnp.asarray(np.moveaxis(d, -1, 0), jnp.float32),
        prob,
        cfg,
        mask=jnp.asarray(mask[None], jnp.float32),
    )
    assert int(res.trace.num_iters) == n_iters
    np.testing.assert_allclose(
        np.asarray(res.trace.obj_vals[: n_iters + 1], np.float64),
        ml_objs,
        rtol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(res.recon[0], np.float64), ml_res, atol=2e-3, rtol=2e-3
    )
    # trajectory must actually move (no trivial agreement)
    assert ml_objs[-1] < 0.5 * ml_objs[0]


def test_poisson_literal_diag_approximation_quantified():
    """Deviation 1 is real: the literal :185-186 per-channel diagonal
    formula and the exact solve of the same system produce genuinely
    different trajectories (they coincide only where TG == 0), and
    both still converge. This pins that the framework's exact solve is
    a deliberate divergence from the text, not a misreading."""
    b, d, mask = _problem(seed=78)
    n_iters = 5
    lit, _ = matlab_poisson_solver(
        b, d, mask, 20.0, 1.0, n_iters,
        exact_solve=False, literal_channel1=False,
    )
    exact, _ = matlab_poisson_solver(
        b, d, mask, 20.0, 1.0, n_iters,
        exact_solve=True, literal_channel1=False,
    )
    assert np.all(np.isfinite(lit)) and np.all(np.isfinite(exact))
    # both decrease the objective from the zero init
    assert lit[-1] < 0.9 * lit[0] and exact[-1] < 0.9 * exact[0]
    # the approximation is measurable
    rel = np.abs(lit[1:] - exact[1:]) / np.abs(exact[1:])
    assert rel.max() > 1e-6
    # ... but not catastrophic at this operating point
    assert rel.max() < 0.5


def test_poisson_literal_channel1_bug_quantified():
    """Deviation 2 is real: exempting/regularizing channel 1 (the
    literal :84/:175 indexing, which hits a learned filter because :7
    appends the dirac last) versus the dirac channel (the :4 comment's
    intent) measurably changes the trajectory."""
    b, d, mask = _problem(seed=79)
    n_iters = 4
    lit, _ = matlab_poisson_solver(
        b, d, mask, 20.0, 1.0, n_iters,
        exact_solve=True, literal_channel1=True,
    )
    intent, _ = matlab_poisson_solver(
        b, d, mask, 20.0, 1.0, n_iters,
        exact_solve=True, literal_channel1=False,
    )
    rel = np.abs(lit[1:] - intent[1:]) / np.abs(intent[1:])
    assert rel.max() > 1e-6
