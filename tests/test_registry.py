"""Multi-tenant bank registry (serve.registry) + engine/fleet
hot-swap (ISSUE 15 tentpole).

Contracts under test:
- BankRegistry: durable manifests (latest wins, history kept,
  torn-tail tolerant), content-addressed bank store, digest identity
  shared with ReconPlan's ``d_digest``, corrupt-payload refusal;
- PlanCache: byte-budgeted LRU with pinning, evict-and-rebuild;
- CodecEngine multi-bank routing: requests route by bank id, results
  BIT-IDENTICAL to fresh single-bank engines, zero XLA compiles
  after warmup even across banks and swaps (the shared
  digest-canonical program);
- zero-downtime hot-swap (the acceptance proof, fleet level):
  continuous two-tenant traffic, one tenant's bank republished under
  a new digest mid-stream — zero lost requests, every pre-swap result
  bit-identical to a fresh old-bank engine, every post-swap result
  bit-identical to a fresh new-bank engine, the cutover visible as a
  ``bank_swap`` event with both digests.
"""
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
    TenantSpec,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    build_plan,
)
from ccsc_code_iccv2017_tpu.serve import (
    BankRegistry,
    CodecEngine,
    PlanCache,
    ServeFleet,
    bank_digest,
)
from ccsc_code_iccv2017_tpu.serve import registry as registry_mod
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError


def _bank(seed=0, k=4, s=3):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return d


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_objective=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _scfg(**kw):
    base = dict(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    base.update(kw)
    return ServeConfig(**base)


def _geom(d):
    return ProblemGeom(d.shape[1:], d.shape[0])


def _req(seed=1, size=12, keep=0.5):
    r = np.random.default_rng(seed)
    x = r.random((size, size)).astype(np.float32)
    m = (r.random((size, size)) < keep).astype(np.float32)
    return x * m, m


# ---------------------------------------------------------------------
# BankRegistry
# ---------------------------------------------------------------------


def test_registry_publish_resolve_roundtrip(tmp_path):
    reg = BankRegistry(str(tmp_path))
    d0, d1 = _bank(0), _bank(1)
    man0 = reg.publish("bank-a", d0, tenant="alpha")
    assert man0["digest"] == bank_digest(d0)
    assert man0["geometry"]["num_filters"] == 4
    assert man0["geometry"]["spatial_support"] == [3, 3]
    # latest wins: a re-publish under a new digest IS the swap trigger
    man1 = reg.publish("bank-a", d1)
    got = reg.resolve("bank-a")
    assert got["digest"] == man1["digest"] == bank_digest(d1)
    assert [m["digest"] for m in reg.history("bank-a")] == [
        man0["digest"], man1["digest"],
    ]
    arr, man = reg.load("bank-a")
    np.testing.assert_array_equal(arr, d1)
    reg.close()


def test_registry_digest_is_the_plan_refusal_digest(tmp_path):
    """Registry identity and ReconPlan's d_digest are the SAME
    fingerprint — routing and plan refusal can never disagree about
    what a bank is."""
    d = _bank(3)
    reg = BankRegistry(str(tmp_path))
    man = reg.publish("b", d)
    plan = build_plan(
        jnp.asarray(d), ReconstructionProblem(_geom(d)), _cfg(),
        (12, 12),
    )
    assert plan.d_digest == man["digest"]
    reg.close()


def test_registry_unknown_and_reopen(tmp_path):
    reg = BankRegistry(str(tmp_path))
    with pytest.raises(CCSCInputError, match="not in the registry"):
        reg.resolve("missing")
    reg.publish("b", _bank(0))
    reg.close()
    # a reopened registry continues the sequence durably
    reg2 = BankRegistry(str(tmp_path))
    man = reg2.publish("b", _bank(1))
    assert man["seq"] == 2
    assert len(reg2.history("b")) == 2
    reg2.close()


def test_registry_torn_manifest_tail_is_dropped(tmp_path):
    reg = BankRegistry(str(tmp_path))
    reg.publish("b", _bank(0))
    reg.close()
    # simulate a writer killed mid-append: torn trailing line
    with open(
        os.path.join(str(tmp_path), "manifest.jsonl"), "a"
    ) as f:
        f.write('{"bank_id": "b", "digest": "dead')
    reg2 = BankRegistry(str(tmp_path))
    assert len(reg2.history("b")) == 1  # torn line dropped, not fatal
    reg2.close()


def test_registry_corrupt_payload_refused(tmp_path):
    reg = BankRegistry(str(tmp_path))
    man = reg.publish("b", _bank(0))
    # corrupt the stored bytes behind the manifest's back
    np.save(os.path.join(str(tmp_path), man["path"]), _bank(9))
    with pytest.raises(CCSCInputError, match="does not match"):
        reg.load("b")
    reg.close()


# ---------------------------------------------------------------------
# PlanCache
# ---------------------------------------------------------------------


def _tiny_plan(seed, spatial=(12, 12)):
    d = _bank(seed)
    return build_plan(
        jnp.asarray(d), ReconstructionProblem(_geom(d)), _cfg(),
        spatial,
    )


def test_plan_cache_lru_eviction_and_pinning():
    p0, p1, p2 = (_tiny_plan(i) for i in range(3))
    one = registry_mod.plan_nbytes(p0)
    assert one > 0
    cache = PlanCache(max_bytes=2 * one)
    assert cache.put("d0", "bk", p0) == []
    assert cache.put("d1", "bk", p1) == []
    cache.get("d0", "bk")  # touch: d1 becomes the LRU victim
    evicted = cache.put("d2", "bk", p2)
    assert evicted == [("d1", "bk")]
    assert cache.get("d1", "bk") is None  # miss -> caller rebuilds
    assert cache.get("d0", "bk") is not None
    # pinned digests survive over-budget inserts
    cache2 = PlanCache(max_bytes=one)
    cache2.put("d0", "bk", p0)
    evicted = cache2.put("d1", "bk", p1, pin={"d0"})
    assert evicted == []  # nothing evictable: d0 pinned, d1 just added
    st = cache2.stats()
    assert st["n_plans"] == 2 and st["plan_bytes"] > st["max_bytes"]


def test_plan_cache_stats_count_hits_misses():
    cache = PlanCache(max_bytes=10**9)
    p = _tiny_plan(0)
    cache.put("d", "bk", p)
    assert cache.get("d", "bk") is not None
    assert cache.get("other", "bk") is None
    st = cache.stats()
    assert st["hits"] == 1 and st["misses"] == 1


# ---------------------------------------------------------------------
# Engine: multi-bank routing + hot-swap
# ---------------------------------------------------------------------


def test_engine_routes_by_bank_id_bit_identical(tmp_path):
    dA, dB = _bank(0), _bank(1)
    geom = _geom(dA)
    eng = CodecEngine(
        jnp.asarray(dA), ReconstructionProblem(geom), _cfg(), _scfg()
    )
    try:
        eng.publish_bank("bank-b", dB)
        b, m = _req(5)
        got_a = eng.reconstruct(b, mask=m)  # default bank
        got_b = eng.reconstruct(b, mask=m, bank_id="bank-b")
        with pytest.raises(CCSCInputError, match="unknown bank id"):
            eng.submit(b, mask=m, bank_id="nope")
        assert eng.bank_ids == ["bank-b"]
        assert eng.bank_digest("bank-b") == bank_digest(dB)
    finally:
        eng.close()
    for d_ref, got in ((dA, got_a), (dB, got_b)):
        ref_eng = CodecEngine(
            jnp.asarray(d_ref), ReconstructionProblem(geom), _cfg(),
            _scfg(),
        )
        try:
            want = ref_eng.reconstruct(b, mask=m)
        finally:
            ref_eng.close()
        np.testing.assert_array_equal(got.recon, want.recon)


def test_engine_hot_swap_zero_compiles_and_parity(tmp_path):
    """The hot-swap core claim: a republished default bank serves new
    admissions from the new plan with ZERO XLA compiles (the bucket
    program is digest-canonical and shared) while pre-swap results
    match the old bank bit-for-bit."""
    dA, dB = _bank(0), _bank(1)
    geom = _geom(dA)
    eng = CodecEngine(
        jnp.asarray(dA), ReconstructionProblem(geom), _cfg(),
        _scfg(metrics_dir=str(tmp_path)),
    )
    try:
        t_ready = time.time()
        b, m = _req(5)
        pre = eng.reconstruct(b, mask=m)
        old, new = eng.publish_bank(None, dB)
        assert (old, new) == (bank_digest(dA), bank_digest(dB))
        post = eng.reconstruct(b, mask=m)
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    compiles = [
        e for e in events
        if e.get("type") == "compile" and e["t"] > t_ready
    ]
    assert compiles == [], "hot-swap must not trigger XLA compiles"
    swaps = [e for e in events if e.get("type") == "bank_swap"]
    assert len(swaps) == 1
    assert swaps[0]["old_digest"] == old
    assert swaps[0]["new_digest"] == new
    builds = [
        e for e in events if e.get("type") == "bank_plan_build"
    ]
    assert len(builds) == 1  # one bucket, one new-bank plan
    for d_ref, got in ((dA, pre), (dB, post)):
        ref = CodecEngine(
            jnp.asarray(d_ref), ReconstructionProblem(geom), _cfg(),
            _scfg(),
        )
        try:
            want = ref.reconstruct(b, mask=m)
        finally:
            ref.close()
        np.testing.assert_array_equal(got.recon, want.recon)


def test_engine_plan_evict_and_rebuild_on_miss(tmp_path, monkeypatch):
    """A plan evicted by the byte budget rebuilds on its next request
    (evict-and-rebuild): the request still serves, bit-identical."""
    # budget fits ~one plan: adding bank B evicts the idle default
    d = _bank(0)
    plan_bytes = registry_mod.plan_nbytes(_tiny_plan(0))
    monkeypatch.setenv(
        "CCSC_BANK_PLAN_CACHE_MB", str(plan_bytes * 1.5 / 1e6)
    )
    dB = _bank(1)
    geom = _geom(d)
    eng = CodecEngine(
        jnp.asarray(d), ReconstructionProblem(geom), _cfg(),
        _scfg(metrics_dir=str(tmp_path)),
    )
    try:
        eng.publish_bank("bank-b", dB)
        b, m = _req(5)
        got = eng.reconstruct(b, mask=m)  # default: rebuilt on miss
        st = eng.plan_cache_stats()
        assert st["evictions"] >= 1
        assert st["misses"] >= 1
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    assert any(
        e.get("type") == "bank_plan_evict" for e in events
    )
    ref = CodecEngine(
        jnp.asarray(d), ReconstructionProblem(geom), _cfg(), _scfg()
    )
    try:
        want = ref.reconstruct(b, mask=m)
    finally:
        ref.close()
    np.testing.assert_array_equal(got.recon, want.recon)


def test_engine_refuses_wrong_geometry_bank():
    d = _bank(0)
    eng = CodecEngine(
        jnp.asarray(d), ReconstructionProblem(_geom(d)), _cfg(),
        _scfg(),
    )
    try:
        with pytest.raises(CCSCInputError):
            eng.add_bank(_bank(1, k=6))  # wrong filter count
    finally:
        eng.close()


# ---------------------------------------------------------------------
# Fleet hot-swap proof (acceptance criterion)
# ---------------------------------------------------------------------


def test_fleet_hot_swap_mid_stream_zero_lost_bit_parity(tmp_path):
    """Continuous two-tenant traffic; tenant beta's bank republished
    under a new digest mid-stream. Zero lost requests; pre-swap beta
    results bit-identical to a fresh old-bank engine, post-swap to a
    fresh new-bank engine; the cutover is a bank_swap event carrying
    both digests; tenant alpha is untouched throughout."""
    dA, dB0, dB1 = _bank(0), _bank(1), _bank(2)
    geom = _geom(dA)
    tenants = (
        TenantSpec(tenant="alpha", bank_id="bank-a"),
        TenantSpec(tenant="beta", bank_id="bank-b"),
    )
    r = np.random.default_rng(11)
    reqs = []
    for _ in range(8):
        x = r.random((12, 12)).astype(np.float32)
        m = (r.random((12, 12)) < 0.5).astype(np.float32)
        reqs.append((x * m, m))
    tenant_of = lambda i: "alpha" if i % 2 == 0 else "beta"
    fleet = ServeFleet(
        dA, ReconstructionProblem(geom), _cfg(), _scfg(),
        FleetConfig(
            replicas=2, metrics_dir=str(tmp_path),
            min_queue_depth=64, verbose="none", tenants=tenants,
        ),
    )
    try:
        fleet.publish_bank("bank-a", dA)
        fleet.publish_bank("bank-b", dB0)
        pre = [
            fleet.submit(
                b, mask=m, tenant=tenant_of(i), key=f"pre{i}"
            )
            for i, (b, m) in enumerate(reqs)
        ]
        old, new = fleet.publish_bank("bank-b", dB1)
        assert (old, new) == (bank_digest(dB0), bank_digest(dB1))
        post = [
            fleet.submit(
                b, mask=m, tenant=tenant_of(i), key=f"post{i}"
            )
            for i, (b, m) in enumerate(reqs)
        ]
        pre_r = [f.result(timeout=120) for f in pre]
        post_r = [f.result(timeout=120) for f in post]
    finally:
        fleet.close()
    assert len(pre_r) == 8 and len(post_r) == 8  # zero lost
    events = obs.read_events(str(tmp_path), recursive=True)
    swaps = [
        e for e in events
        if e.get("type") == "bank_swap"
        and e.get("replica_id") is None
        and e.get("bank_id") == "bank-b"
        and e.get("old_digest") == old
    ]
    assert len(swaps) == 1 and swaps[0]["new_digest"] == new

    def oracle(d_ref, items):
        eng = CodecEngine(
            jnp.asarray(d_ref), ReconstructionProblem(geom), _cfg(),
            _scfg(),
        )
        try:
            return [eng.reconstruct(b, mask=m) for b, m in items]
        finally:
            eng.close()

    beta_items = [reqs[i] for i in range(8) if i % 2 == 1]
    for got, want in zip(
        [pre_r[i] for i in range(8) if i % 2 == 1],
        oracle(dB0, beta_items),
    ):
        np.testing.assert_array_equal(got.recon, want.recon)
    for got, want in zip(
        [post_r[i] for i in range(8) if i % 2 == 1],
        oracle(dB1, beta_items),
    ):
        np.testing.assert_array_equal(got.recon, want.recon)
    alpha_items = [reqs[i] for i in range(8) if i % 2 == 0]
    alpha_want = oracle(dA, alpha_items)
    for got, want in zip(
        [pre_r[i] for i in range(8) if i % 2 == 0]
        + [post_r[i] for i in range(8) if i % 2 == 0],
        alpha_want + alpha_want,
    ):
        np.testing.assert_array_equal(got.recon, want.recon)


def test_hot_swap_retires_superseded_digests(tmp_path):
    """Continuous republish must not accumulate every superseded
    bank forever: once nothing references an old digest (not routed,
    no queued/assigned request bound to it), a later publish's sweep
    drops its retained bytes and cached plans — while a digest with
    queued work is refused retirement and its requests still
    finish."""
    d0 = _bank(0)
    geom = _geom(d0)
    eng = CodecEngine(
        jnp.asarray(d0), ReconstructionProblem(geom), _cfg(),
        _scfg(),
    )
    try:
        b, m = _req(5)
        for seed in (1, 2, 3):
            eng.publish_bank(None, _bank(seed))
            eng.reconstruct(b, mask=m)  # drain so old goes idle
        # only the routed digest's bytes remain retained
        assert set(eng._banks) == {bank_digest(_bank(3))}
        assert eng._plan_cache.digests() == [bank_digest(_bank(3))]
        # a still-referenced digest (here: routed) refuses retirement
        assert not eng.retire_bank(eng.bank_digest(None))
    finally:
        eng.close()
    # fleet sweep: same contract across replicas
    fleet = ServeFleet(
        d0, ReconstructionProblem(geom), _cfg(), _scfg(),
        FleetConfig(
            replicas=1, metrics_dir=str(tmp_path),
            min_queue_depth=64, verbose="none",
        ),
    )
    try:
        for seed in (1, 2, 3):
            fleet.publish_bank("bank-x", _bank(seed))
            b, m = _req(5)
            fleet.submit(b, mask=m, bank_id="bank-x").result(
                timeout=120
            )
        fleet.publish_bank("bank-x", _bank(4))
        assert set(fleet._bank_arrays) == {
            bank_digest(d0), bank_digest(_bank(4))
        }
    finally:
        fleet.close()


def test_fleet_restart_republishes_banks(tmp_path):
    """A replica killed AFTER extra banks were published must come
    back able to serve them: the restart republishes every retained
    bank before the replacement takes work."""
    from ccsc_code_iccv2017_tpu.utils import faults

    dA, dB = _bank(0), _bank(1)
    geom = _geom(dA)
    old_env = {
        k: os.environ.get(k)
        for k in (
            "CCSC_FAULT_ENGINE_KILL_REQ",
            "CCSC_FAULT_ENGINE_KILL_REPLICA",
        )
    }
    os.environ["CCSC_FAULT_ENGINE_KILL_REQ"] = "2"
    os.environ["CCSC_FAULT_ENGINE_KILL_REPLICA"] = "0"
    faults.reset()
    try:
        fleet = ServeFleet(
            dA, ReconstructionProblem(geom), _cfg(), _scfg(),
            FleetConfig(
                replicas=1, metrics_dir=str(tmp_path),
                min_queue_depth=64, restart_backoff_s=0.05,
                verbose="none",
            ),
        )
        try:
            fleet.publish_bank("bank-b", dB)
            futs = []
            for i in range(6):
                b, m = _req(20 + i)
                futs.append(
                    fleet.submit(
                        b, mask=m, bank_id="bank-b", key=f"k{i}"
                    )
                )
            results = [f.result(timeout=180) for f in futs]
            assert len(results) == 6
        finally:
            fleet.close()
        events = obs.read_events(str(tmp_path), recursive=True)
        assert any(
            e.get("type") == "fleet_replica_dead" for e in events
        )
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        faults.reset()
