"""Multi-tenant admission (serve.tenancy / serve.slo.TenantSlos /
serve.metricsd labels / capture+replay routing) — ISSUE 15.

Contracts under test:
- TenantSpec validation + the CLI spec grammar (parse_tenant_spec);
- WeightedFairScheduler: weighted shares, FIFO within a tenant,
  requeue-to-front with virtual-cost refund, idle tenants bank no
  credit;
- the ISOLATION proof (acceptance criterion): with per-tenant quotas
  set, a bursting tenant receives explicit Overloaded rejections
  (tenant_reject events) while the other tenant's requests all serve
  and its p99 — from its OWN SLO histogram — stays within its
  declared target for the whole run;
- per-tenant labels on the Prometheus rendering (tenant series +
  labeled histograms), snapshot format stamp with
  parse_snapshot_stamp unchanged;
- mixed-tenant capture/replay: bank_id/tenant recorded per request
  and replays route by them — bit parity per bank.
"""
import types

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
    TenantSpec,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import (
    Overloaded,
    ServeFleet,
    TenantSlos,
    WeightedFairScheduler,
    parse_tenant_spec,
)
from ccsc_code_iccv2017_tpu.serve.metricsd import (
    parse_snapshot_stamp,
    render_prometheus,
)
from ccsc_code_iccv2017_tpu.serve.tenancy import TenantTable
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError


def _bank(seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return d


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_objective=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _req(seed=1):
    r = np.random.default_rng(seed)
    x = r.random((12, 12)).astype(np.float32)
    m = (r.random((12, 12)) < 0.5).astype(np.float32)
    return x * m, m


# ---------------------------------------------------------------------
# specs + table
# ---------------------------------------------------------------------


def test_tenant_spec_validation():
    with pytest.raises(ValueError, match="non-empty"):
        TenantSpec(tenant="")
    with pytest.raises(ValueError, match="weight"):
        TenantSpec(tenant="t", weight=0.0)
    with pytest.raises(ValueError, match="quota"):
        TenantSpec(tenant="t", quota=0)
    with pytest.raises(ValueError, match="slo_p99_ms"):
        TenantSpec(tenant="t", slo_p99_ms=-1.0)
    with pytest.raises(ValueError, match="duplicate tenant"):
        FleetConfig(
            tenants=(
                TenantSpec(tenant="a"), TenantSpec(tenant="a"),
            )
        )


def test_parse_tenant_spec_grammar():
    s = parse_tenant_spec(
        "mobile:bank=bank-m,p50=50,p99=250,quota=16,weight=2"
    )
    assert s == TenantSpec(
        tenant="mobile", bank_id="bank-m", slo_p50_ms=50.0,
        slo_p99_ms=250.0, quota=16, weight=2.0,
    )
    assert parse_tenant_spec("web") == TenantSpec(tenant="web")
    with pytest.raises(ValueError, match="bad entry"):
        parse_tenant_spec("web:bogus=1")
    with pytest.raises(ValueError, match="not a valid"):
        parse_tenant_spec("web:quota=many")


def test_tenant_table_routing_and_quota(monkeypatch):
    table = TenantTable(
        (
            TenantSpec(tenant="a", bank_id="bank-a", weight=3.0),
            TenantSpec(tenant="b", quota=7, weight=1.0),
        )
    )
    assert table.route("a", None) == "bank-a"
    assert table.route("a", "explicit") == "explicit"  # request wins
    assert table.route(None, None) is None
    assert table.route("b", None) is None  # no declared bank
    with pytest.raises(CCSCInputError, match="unknown tenant"):
        table.check("typo")
    table.check(None)  # untenanted always passes
    assert table.quota("b", 100) == 7  # declared wins
    # derived: ceiling x weight share x CCSC_TENANT_QUOTA_FRAC
    assert table.quota("a", 100) == int(100 * 0.75 * 0.5 + 0.999)
    assert table.quota(None, 100) is None


# ---------------------------------------------------------------------
# weighted-fair scheduler
# ---------------------------------------------------------------------


def _item(tenant, n):
    return types.SimpleNamespace(tenant=tenant, n=n)


def test_weighted_fair_shares_and_fifo_within_tenant():
    table = TenantTable(
        (
            TenantSpec(tenant="heavy", weight=3.0),
            TenantSpec(tenant="light", weight=1.0),
        )
    )
    q = WeightedFairScheduler(table)
    for i in range(12):
        q.append(_item("heavy", i))
    for i in range(4):
        q.append(_item("light", i))
    order = [q.popleft() for _ in range(16)]
    assert len(q) == 0
    # 3:1 share over the first 8 pops: ~6 heavy, ~2 light
    first8 = [it.tenant for it in order[:8]]
    assert first8.count("heavy") == 6
    assert first8.count("light") == 2
    # FIFO within each tenant
    heavy_seq = [it.n for it in order if it.tenant == "heavy"]
    light_seq = [it.n for it in order if it.tenant == "light"]
    assert heavy_seq == sorted(heavy_seq)
    assert light_seq == sorted(light_seq)


def test_weighted_fair_requeue_front_and_refund():
    q = WeightedFairScheduler(TenantTable(None))
    q.append(_item("t", 0))
    q.append(_item("t", 1))
    first = q.popleft()
    q.appendleft(first)  # casualty requeue
    assert q.popleft().n == 0  # back at the FRONT of its lane
    assert q.popleft().n == 1


def test_weighted_fair_idle_tenant_banks_no_credit():
    table = TenantTable(
        (
            TenantSpec(tenant="busy", weight=1.0),
            TenantSpec(tenant="idle", weight=1.0),
        )
    )
    q = WeightedFairScheduler(table)
    for i in range(50):
        q.append(_item("busy", i))
    for _ in range(50):
        q.popleft()
    # idle arrives late: it must NOT get 50 consecutive pops of
    # banked credit — service interleaves from the floor
    for i in range(4):
        q.append(_item("idle", i))
        q.append(_item("busy", 100 + i))
    got = [q.popleft().tenant for _ in range(8)]
    assert got.count("idle") == 4 and got.count("busy") == 4
    assert sorted(set(got[:2])) == ["busy", "idle"]  # interleaved


def test_scheduler_untenanted_is_fifo():
    q = WeightedFairScheduler(TenantTable(None))
    for i in range(5):
        q.append(_item(None, i))
    assert [q.popleft().n for _ in range(5)] == list(range(5))
    with pytest.raises(IndexError):
        q.popleft()


# ---------------------------------------------------------------------
# TenantSlos
# ---------------------------------------------------------------------


def test_tenant_slos_breach_and_snapshot_stamps():
    slos = TenantSlos(
        (
            TenantSpec(tenant="a", slo_p99_ms=10.0),
            TenantSpec(tenant="b", slo_p99_ms=1e6),
        ),
        check_s=0.0,
    )
    for _ in range(50):
        slos.observe("a", 500.0)  # way past a's target
        slos.observe("b", 500.0)  # far inside b's
    slos.observe(None, 1e9)  # untenanted: ignored
    breaches, snaps = slos.final()
    assert [b["tenant"] for b in breaches] == ["a"]
    assert breaches[0]["quantile"] == 0.99
    by_tenant = {s["tenant"]: s for s in snaps}
    assert by_tenant["a"]["target_p99_ms"] == 10.0
    assert by_tenant["b"]["n"] == 50
    assert slos.percentile("a", 0.99) >= 10.0


# ---------------------------------------------------------------------
# the isolation proof (acceptance criterion)
# ---------------------------------------------------------------------


def test_quota_isolation_burst_rejected_other_tenant_holds(tmp_path):
    """Tenant 'burst' floods past its quota: it gets explicit
    Overloaded refusals (tenant_reject events, counted per tenant)
    while tenant 'steady' serves every request and its p99 — from
    its own histogram — stays within its declared target."""
    d = _bank(0)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    steady_p99_ms = 60_000.0  # generous CPU-CI band; the point is
    # that the claim is judged from steady's OWN histogram
    tenants = (
        TenantSpec(tenant="burst", quota=2, weight=1.0),
        TenantSpec(
            tenant="steady", slo_p99_ms=steady_p99_ms, weight=1.0,
            quota=64,  # explicit headroom: the proof is about
            # burst's quota, steady must only be bounded by the
            # global ceiling
        ),
    )
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(),
        ServeConfig(
            buckets=((1, (12, 12)),), max_wait_ms=1.0,
            verbose="none",
        ),
        FleetConfig(
            replicas=1, metrics_dir=str(tmp_path),
            min_queue_depth=64, verbose="none", tenants=tenants,
        ),
    )
    n_rejected = 0
    steady_futs = []
    burst_futs = []
    try:
        # flood the burst tenant far past its quota of 2 queued
        for i in range(30):
            b, m = _req(i)
            try:
                burst_futs.append(
                    fleet.submit(
                        b, mask=m, tenant="burst", key=f"burst{i}"
                    )
                )
            except Overloaded as e:
                n_rejected += 1
                assert e.retry_after_s > 0
            # steady traffic keeps being admitted regardless
            bs, ms = _req(100 + i)
            steady_futs.append(
                fleet.submit(
                    bs, mask=ms, tenant="steady", key=f"steady{i}"
                )
            )
        steady_r = [f.result(timeout=300) for f in steady_futs]
        burst_r = [f.result(timeout=300) for f in burst_futs]
        st = fleet.stats()
    finally:
        fleet.close()
    assert n_rejected >= 1, "the burst must hit its quota"
    assert len(steady_r) == 30  # every steady request served
    assert len(burst_r) == len(burst_futs)  # admitted ones all serve
    assert st["tenants"]["burst"]["rejected"] == n_rejected
    assert st["tenants"]["steady"]["rejected"] == 0
    # the isolation claim, judged from steady's own histogram
    p99_s = st["tenants"]["steady"]["p99_latency_s"]
    assert p99_s is not None and p99_s * 1e3 <= steady_p99_ms
    events = obs.read_events(str(tmp_path), recursive=True)
    rejects = [
        e for e in events if e.get("type") == "tenant_reject"
    ]
    assert len(rejects) == n_rejected
    assert all(e["tenant"] == "burst" for e in rejects)
    assert all(e["quota"] == 2 for e in rejects)
    # steady never breached its declared band
    assert not any(
        e.get("type") == "slo_breach"
        and e.get("tenant") == "steady"
        for e in events
    )
    # closing per-tenant histogram flush landed (offline TENANTS
    # recomputation is possible from the stream alone)
    t_hists = [
        e for e in events
        if e.get("type") == "slo_histogram"
        and e.get("tenant") == "steady"
    ]
    assert t_hists and t_hists[-1]["target_p99_ms"] == steady_p99_ms


def test_unknown_tenant_refused(tmp_path):
    d = _bank(0)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(),
        ServeConfig(
            buckets=((2, (12, 12)),), max_wait_ms=2.0,
            verbose="none",
        ),
        FleetConfig(
            replicas=1, min_queue_depth=64, verbose="none",
            tenants=(TenantSpec(tenant="a"),),
        ),
    )
    try:
        b, m = _req(1)
        with pytest.raises(CCSCInputError, match="unknown tenant"):
            fleet.submit(b, mask=m, tenant="typo")
        fleet.submit(b, mask=m).result(timeout=120)  # None: fine
    finally:
        fleet.close()


# ---------------------------------------------------------------------
# metricsd: per-tenant labels + snapshot format stamp
# ---------------------------------------------------------------------


def test_render_prometheus_labeled_counters_and_histograms():
    metrics = {
        "counters": {"requests_total": 5},
        "gauges": {"banks": 2},
        "labeled_counters": [
            ("tenant_requests_total", {"tenant": "a"}, 3),
            ("tenant_requests_total", {"tenant": "b"}, 2),
            ("tenant_rejected_total", {"tenant": "b"}, 4),
        ],
        "histograms": [
            (
                "latency_ms",
                {"phase": "total", "tenant": "a"},
                {
                    "bounds_ms": [1.0, 10.0],
                    "counts": [2, 1, 0],
                    "n": 3,
                    "sum_ms": 8.0,
                },
            )
        ],
    }
    text = render_prometheus(metrics)
    assert 'ccsc_tenant_requests_total{tenant="a"} 3' in text
    assert 'ccsc_tenant_requests_total{tenant="b"} 2' in text
    assert 'ccsc_tenant_rejected_total{tenant="b"} 4' in text
    # one TYPE line per metric name, not per label set
    assert text.count("# TYPE ccsc_tenant_requests_total") == 1
    assert (
        'ccsc_latency_ms_bucket{le="1.0",phase="total",tenant="a"} 2'
        in text
    )


def test_fleet_metrics_carry_tenant_series(tmp_path):
    d = _bank(0)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), _cfg(),
        ServeConfig(
            buckets=((2, (12, 12)),), max_wait_ms=2.0,
            verbose="none",
        ),
        FleetConfig(
            replicas=1, min_queue_depth=64, verbose="none",
            tenants=(
                TenantSpec(tenant="a", slo_p99_ms=60_000.0),
            ),
        ),
    )
    try:
        b, m = _req(1)
        fleet.submit(b, mask=m, tenant="a", key="k0").result(
            timeout=120
        )
        metrics = fleet.metrics()
        text = render_prometheus(metrics)
    finally:
        fleet.close()
    assert ("tenant_requests_total", {"tenant": "a"}, 1) in (
        metrics["labeled_counters"]
    )
    assert 'ccsc_tenant_requests_total{tenant="a"} 1' in text
    assert 'tenant="a"' in text and "ccsc_latency_ms_bucket" in text


def test_snapshot_format_stamp_parse_unchanged(tmp_path):
    from ccsc_code_iccv2017_tpu.serve.metricsd import (
        SNAPSHOT_FORMAT,
        MetricsD,
    )

    snap = str(tmp_path / "metrics.prom")
    md = MetricsD(
        lambda: {"counters": {"requests_total": 1}, "gauges": {}},
        port=None,
        snapshot_path=snap,
        run_id="fleet-test-1",
    ).start()
    md.stop()
    text = open(snap).read()
    assert f"ccsc_snapshot_format {SNAPSHOT_FORMAT}" in text
    stamp = parse_snapshot_stamp(snap)  # the unchanged contract
    assert stamp is not None
    assert stamp["run_id"] == "fleet-test-1"
    assert stamp["timestamp"] > 0 and "age_s" in stamp


# ---------------------------------------------------------------------
# mixed-tenant capture -> replay (bit parity per bank)
# ---------------------------------------------------------------------


def test_mixed_tenant_capture_replays_bit_faithfully(tmp_path):
    import os

    from ccsc_code_iccv2017_tpu.serve import capture as cap
    from ccsc_code_iccv2017_tpu.serve.replay import ReplayDriver

    dA, dB = _bank(0), _bank(1)
    geom = ProblemGeom(dA.shape[1:], dA.shape[0])
    tenants = (
        TenantSpec(tenant="alpha", bank_id="bank-a"),
        TenantSpec(tenant="beta", bank_id="bank-b"),
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )

    def fleet_cfg(mdir, capture_dir):
        return FleetConfig(
            replicas=1, metrics_dir=mdir, capture_dir=capture_dir,
            min_queue_depth=64, verbose="none", tenants=tenants,
        )

    cap_dir = str(tmp_path / "capture")
    fleet = ServeFleet(
        dA, ReconstructionProblem(geom),
        _cfg(track_psnr=True), scfg,
        fleet_cfg(str(tmp_path / "m-serve"), cap_dir),
    )
    try:
        fleet.publish_bank("bank-a", dA)
        fleet.publish_bank("bank-b", dB)
        futs = []
        for i in range(8):
            b, m = _req(i)
            futs.append(
                fleet.submit(
                    b, mask=m,
                    tenant="alpha" if i % 2 == 0 else "beta",
                    key=f"k{i}",
                )
            )
        for f in futs:
            f.result(timeout=120)
    finally:
        fleet.close()
    recs = cap.read_workload(cap_dir)
    assert len(recs) == 8
    assert {r["tenant"] for r in recs} == {"alpha", "beta"}
    assert {r["bank_id"] for r in recs} == {"bank-a", "bank-b"}
    # replay against a FRESH fleet with the same banks published:
    # every replayed request must route to ITS bank and be bit-exact
    fresh = ServeFleet(
        dA, ReconstructionProblem(geom),
        _cfg(track_psnr=True), scfg,
        fleet_cfg(str(tmp_path / "m-replay"), ""),
    )
    try:
        fresh.publish_bank("bank-a", dA)
        fresh.publish_bank("bank-b", dB)
        rep = ReplayDriver(
            cap_dir, metrics_dir=str(tmp_path / "m-replay")
        ).replay(fresh, speed=0.0, mode="open")
    finally:
        fresh.close()
    assert rep["n_replayed"] == 8
    assert rep["n_lost"] == 0
    assert rep["n_mismatched"] == 0
    assert rep["n_exact"] == 8
    assert os.path.isdir(cap_dir)
