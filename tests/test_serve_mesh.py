"""Mesh-sharded serving replicas (ISSUE 14): a bucket's slots served
from a device mesh via shard_map, not a single core.

Contracts under test (all on the conftest's 8 forced host devices —
the same virtual pod MULTICHIP_r05.json proved sharded-reconstruct
parity on):

- EXACT-BUCKET BIT-IDENTITY: a mesh engine's result at a bucket shape
  equals the single-device engine's BITWISE — recon, trace values,
  stopping iteration — for both (batch,) and (batch, freq) meshes
  (each slot stays its own n=1 solve; the plan's per-frequency solve
  factors are replicated and sliced per device);
- padded-bucket requests match the exact-shape solve on the valid
  region to the same boundary tolerance as the single-device engine;
- ZERO compiles after warmup, from the obs stream, and the stream
  records the replica's device topology (serve_ready devices/mesh);
- actionable refusals: ServeConfig/build_plan refuse a mesh whose
  batch axis does not divide a bucket's slots (bucket list in the
  error); reconstruct(plan=..., mesh=...) points at the engine path;
  a mesh the device pool cannot back names the forced-host-device
  recipe (CCSC_SERVE_MESH_STRICT=0 falls back single-device);
- FLEET: a mesh replica among single-device replicas — kill it
  mid-stream; zero lost, results bit-identical, the casualty rejoins
  on the same device slice; capacity_hint counts mesh devices and
  the derived admission ceiling scales by per-replica device count
  (utils.perfmodel.fleet_serving_bound);
- LEDGER: the bench's mesh arm lands as its OWN knob-digest
  configuration, and perf_gate judges an injected 0.5x record
  against the mesh key's history (exit-1 class verdict).
"""
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    build_plan,
    reconstruct,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet
from ccsc_code_iccv2017_tpu.utils import faults, obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError

needs8 = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="needs 8 (forced host) devices — run under XLA_FLAGS="
    "--xla_force_host_platform_device_count=8",
)


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    for v in (
        "CCSC_SERVE_MESH",
        "CCSC_SERVE_MESH_STRICT",
        "CCSC_FAULT_ENGINE_KILL_REQ",
        "CCSC_FAULT_ENGINE_KILL_REPLICA",
        "CCSC_WATCHDOG_MIN_S",
        "CCSC_WATCHDOG_COMPILE_S",
        "CCSC_PERF_LEDGER",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


def _bank(k=6, s=5, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=8, tol=1e-4,
        verbose="none", track_objective=True, track_psnr=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _req(size, seed=1, keep=0.5):
    r = np.random.default_rng(seed)
    x = r.random((size, size)).astype(np.float32)
    m = (r.random((size, size)) < keep).astype(np.float32)
    return x, m


def _engine(d, cfg, buckets, tmp_path=None, **kw):
    scfg = ServeConfig(
        buckets=buckets,
        max_wait_ms=kw.pop("max_wait_ms", 10.0),
        metrics_dir=str(tmp_path) if tmp_path is not None else None,
        verbose="none",
        **kw,
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)


def _serve_all(eng, reqs):
    futs = [
        eng.submit(x * m, mask=m, x_orig=x) for x, m in reqs
    ]
    return [f.result(timeout=300) for f in futs]


# ------------------------------------------------------- exact parity


@needs8
@pytest.mark.parametrize("mesh_shape", [(2,), (2, 2), (4, 2)])
def test_mesh_engine_bit_identical_on_exact_buckets(mesh_shape):
    """The tentpole contract: the shard_map'd bucket program returns
    BITWISE the single-device program's results — per-slot recon,
    objective/PSNR traces, and stopping iteration — for batch-only
    and batch x freq meshes. (Shapes here keep >= 2 slots per device:
    XLA's batch-1 specialization can round ~1 ulp differently when a
    mesh leaves a lone slot on a device.)"""
    d = _bank()
    cfg = _cfg()
    slots = 2 * mesh_shape[0]  # keep >= 2 slots per device
    buckets = ((slots, (24, 24)),)
    reqs = [_req(24, seed=100 + i) for i in range(slots)]
    ref_eng = _engine(d, cfg, buckets, mesh_shape=())
    try:
        ref = _serve_all(ref_eng, reqs)
    finally:
        ref_eng.close()
    eng = _engine(d, cfg, buckets, mesh_shape=mesh_shape)
    try:
        assert eng.devices == int(np.prod(mesh_shape))
        assert eng.mesh_shape == mesh_shape
        res = _serve_all(eng, reqs)
    finally:
        eng.close()
    for a, b in zip(ref, res):
        np.testing.assert_array_equal(b.recon, a.recon)
        np.testing.assert_array_equal(
            np.asarray(b.trace.obj_vals), np.asarray(a.trace.obj_vals)
        )
        np.testing.assert_array_equal(
            np.asarray(b.trace.psnr_vals),
            np.asarray(a.trace.psnr_vals),
        )
        assert int(b.trace.num_iters) == int(a.trace.num_iters)


@needs8
def test_mesh_padded_bucket_matches_exact_shape_on_valid_region():
    """A request smaller than its bucket on a mesh engine: the pad
    region is mask-excluded exactly as on a single device, so the
    valid-region result matches the exact-shape direct solve to
    boundary tolerance."""
    d = _bank()
    cfg = _cfg(max_it=20)
    eng = _engine(d, cfg, ((4, (32, 32)),), mesh_shape=(2,))
    try:
        x, m = _req(26, seed=3)
        res = eng.reconstruct(x * m, mask=m)
        assert res.bucket == "4@32x32"
        assert res.recon.shape == (26, 26)
    finally:
        eng.close()
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    direct = reconstruct(
        jnp.asarray((x * m)[None]), d, ReconstructionProblem(geom),
        cfg, mask=jnp.asarray(m[None]),
    )
    ref = np.asarray(direct.recon[0])
    rel = np.abs(res.recon - ref).max() / max(np.abs(ref).max(), 1e-9)
    assert rel < 0.05, rel


@needs8
def test_mesh_zero_compiles_after_warmup_and_topology_in_stream(
    tmp_path,
):
    """Zero-compile serving holds for the shard_map'd programs too,
    asserted from the obs stream; serve_warmup/serve_ready record the
    replica's device topology."""
    d = _bank()
    eng = _engine(
        d, _cfg(), ((4, (24, 24)),), tmp_path=tmp_path,
        mesh_shape=(2, 2),
    )
    try:
        t_ready = time.time()
        for seed in (1, 5, 9):
            x, m = _req(24, seed=seed)
            eng.reconstruct(x * m, mask=m)
        x, m = _req(20, seed=11)  # padded into the same bucket
        eng.reconstruct(x * m, mask=m)
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    compiles = [e for e in events if e.get("type") == "compile"]
    assert compiles, "warmup must have recorded compile events"
    after = [e for e in compiles if e["t"] > t_ready]
    assert after == [], [e.get("fun_name") for e in after]
    ready = next(e for e in events if e.get("type") == "serve_ready")
    assert ready["devices"] == 4
    assert ready["mesh"] == [2, 2]
    warm = [e for e in events if e.get("type") == "serve_warmup"]
    assert all(w["devices"] == 4 for w in warm)
    # the knob dict carries the topology: the perf-ledger key of a
    # mesh engine's records is its own configuration
    assert ready["knobs"]["devices"] == 4
    assert ready["knobs"]["mesh"] == "2x2"
    meta = next(e for e in events if e.get("type") == "run_meta")
    assert meta.get("serve_devices") == 4


# ------------------------------------------------- collective budgets


@needs8
def test_batch_mesh_program_lowers_to_zero_collectives(tmp_path):
    """The ISSUE-20 acceptance property, asserted live (this test
    rides the CCSC_CI_DEVICES=8 ci.sh leg): a batch-only mesh bucket
    program contains ZERO collective HLO ops — the solve factors are
    replicated small constants and every slot's solve decouples, so
    any collective in the text is a lowering bug. The comm_audit
    event records the passing verdict per bucket."""
    d = _bank()
    eng = _engine(
        d, _cfg(max_it=2, tol=0.0), ((8, (12, 12)),),
        tmp_path=tmp_path, mesh_shape=(8,),
    )
    try:
        counts = eng.comm_counts
        assert counts, "mesh warmup must audit every bucket program"
        assert all(c["total"] == 0 for c in counts.values()), counts
    finally:
        eng.close()
    audits = [
        e for e in obs.read_events(str(tmp_path))
        if e.get("type") == "comm_audit"
    ]
    assert audits
    assert all(e["ok"] is True for e in audits)
    assert all(e["budget"] == 0 for e in audits)
    assert all(e["total"] == 0 for e in audits)


@needs8
def test_freq_mesh_program_meets_declared_budget(tmp_path):
    """A (batch, freq) program pays its communication in exactly one
    op class — the z-solve-tail spectrum all-gather — and stays at or
    under CCSC_COMM_BUDGET_FREQ (default 1) TOTAL ops across classes:
    a refactor that swaps the gather for a gather plus a reduce fails
    here before it can land as a throughput cliff."""
    from ccsc_code_iccv2017_tpu.analysis import comms

    d = _bank()
    eng = _engine(
        d, _cfg(max_it=2, tol=0.0), ((4, (24, 24)),),
        tmp_path=tmp_path, mesh_shape=(2, 2),
    )
    try:
        counts = eng.comm_counts
        assert counts
        budget = comms.declared_budget((2, 2))
        for c in counts.values():
            assert 0 < c["total"] <= budget, c
            # all communication is the one gather class
            assert c["all_gather"] == c["total"], c
    finally:
        eng.close()
    audits = [
        e for e in obs.read_events(str(tmp_path))
        if e.get("type") == "comm_audit"
    ]
    assert audits
    assert all(e["ok"] is True for e in audits)
    assert all(e["budget"] == budget for e in audits)


# ---------------------------------------------------------- refusals


def test_serveconfig_refuses_non_dividing_mesh_with_bucket_list():
    with pytest.raises(ValueError, match=r"divide.*\(3, \(16, 16\)\)"):
        ServeConfig(
            buckets=((4, (24, 24)), (3, (16, 16))), mesh_shape=(2,)
        )
    # () is the explicit single-device pin, always valid
    scfg = ServeConfig(buckets=((3, (16, 16)),), mesh_shape=())
    assert scfg.mesh_shape == ()
    with pytest.raises(ValueError, match="mesh_devices"):
        ServeConfig(
            buckets=((2, (16, 16)),), mesh_shape=(2,),
            mesh_devices=(0,),
        )
    # spec STRINGS are refused — "12" iterated as characters would
    # silently become a (1, 2) mesh
    with pytest.raises(ValueError, match="is a string"):
        ServeConfig(buckets=((2, (16, 16)),), mesh_shape="12")


def test_build_plan_refuses_incompatible_mesh():
    d = _bank()
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    prob = ReconstructionProblem(geom)
    cfg = _cfg()
    buckets = ((3, (16, 16)),)
    with pytest.raises(ValueError, match=r"batch axis 2.*\(3, \(16, 16\)\)"):
        build_plan(
            d, prob, cfg, (16, 16), mesh_shape=(2,), slots=3,
            buckets=buckets,
        )
    # freq axis must divide the FFT domain's bin count
    with pytest.raises(ValueError, match="freq axis 7"):
        build_plan(
            d, prob, cfg, (16, 16), mesh_shape=(2, 7), slots=2,
        )
    # a compatible mesh builds the SAME plan arrays (replicated)
    p_mesh = build_plan(
        d, prob, cfg, (16, 16), mesh_shape=(2,), slots=4,
        buckets=((4, (16, 16)),),
    )
    p_plain = build_plan(d, prob, cfg, (16, 16))
    np.testing.assert_array_equal(
        np.asarray(p_mesh.kern.dinv), np.asarray(p_plain.kern.dinv)
    )


def test_reconstruct_plan_mesh_refusal_points_at_engine_path():
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    d = _bank()
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    prob = ReconstructionProblem(geom)
    cfg = _cfg()
    plan = build_plan(d, prob, cfg, (16, 16))
    x, m = _req(16)
    with pytest.raises(ValueError, match="mesh_shape"):
        reconstruct(
            jnp.asarray((x * m)[None] * np.ones((2, 1, 1), np.float32)),
            d, prob, cfg, mask=jnp.asarray(np.stack([m, m])),
            mesh=block_mesh(2), plan=plan,
        )


def test_mesh_strict_refusal_names_recipe_and_nonstrict_falls_back(
    monkeypatch,
):
    d = _bank()
    with pytest.raises(
        CCSCInputError, match="xla_force_host_platform_device_count"
    ):
        _engine(d, _cfg(), ((64, (16, 16)),), mesh_shape=(64,))
    monkeypatch.setenv("CCSC_SERVE_MESH_STRICT", "0")
    eng = _engine(d, _cfg(), ((64, (16, 16)),), mesh_shape=(64,))
    try:
        assert eng.devices == 1  # fell back single-device
        assert eng.mesh_shape is None
        x, m = _req(16)
        assert eng.reconstruct(x * m, mask=m).recon.shape == (16, 16)
    finally:
        eng.close()


@needs8
def test_env_mesh_resolution_and_off_sentinel(monkeypatch):
    """CCSC_SERVE_MESH arms a None-mesh_shape engine; mesh_shape=()
    pins single-device even with the knob set (the bench baseline's
    contract)."""
    monkeypatch.setenv("CCSC_SERVE_MESH", "2")
    d = _bank()
    eng = _engine(d, _cfg(max_it=4), ((2, (16, 16)),))
    try:
        assert eng.devices == 2
        assert eng.mesh_shape == (2,)
    finally:
        eng.close()
    eng = _engine(d, _cfg(max_it=4), ((2, (16, 16)),), mesh_shape=())
    try:
        assert eng.devices == 1
    finally:
        eng.close()


# ------------------------------------------------- fleet: mixed shapes


@needs8
def test_fleet_mixed_mesh_chaos_kill_zero_lost_bit_identical(
    tmp_path, monkeypatch,
):
    """One mesh replica among single-device replicas; the MESH
    replica is killed mid-stream. Zero requests lost, every result
    bit-identical to an unfaulted single engine, and the casualty
    rejoins on its own device slice with the same topology."""
    # kill on the FIRST taken request: the mesh replica's dispatch is
    # the slower one on faked CPU devices, so its sibling can drain
    # the short stream before it ever takes a second batch
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REPLICA", "0")
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "0.4")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "0.4")
    faults.reset()
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=4, tol=0.0, track_psnr=False)
    buckets = ((4, (12, 12)),)
    reqs = [_req(12, seed=200 + i) for i in range(10)]

    geom = ProblemGeom(d.shape[1:], d.shape[0])
    ref_eng = CodecEngine(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(buckets=buckets, max_wait_ms=2.0, verbose="none"),
    )
    try:
        futs = [ref_eng.submit(x * m, mask=m) for x, m in reqs]
        ref = [f.result(timeout=180) for f in futs]
    finally:
        ref_eng.close()

    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(buckets=buckets, max_wait_ms=2.0, verbose="none"),
        FleetConfig(
            replicas=2,
            replica_meshes=((2,), None),
            min_queue_depth=64, restart_backoff_s=0.05,
            heartbeat_s=0.2, health_interval_s=0.05, verbose="none",
            metrics_dir=str(tmp_path),
        ),
    )
    try:
        assert fleet.total_devices == 3  # 2 (mesh) + 1
        assert fleet.capacity_hint == 4 * 3
        futs = [
            fleet.submit(x * m, mask=m, key=f"k{i}")
            for i, (x, m) in enumerate(reqs)
        ]
        res = [f.result(timeout=300) for f in futs]
        assert len(res) == 10
        for i in range(10):
            np.testing.assert_array_equal(res[i].recon, ref[i].recon)
            assert int(res[i].trace.num_iters) == int(
                ref[i].trace.num_iters
            )
        # the mesh casualty rejoins — with its mesh topology intact
        deadline = time.monotonic() + 120
        live = []
        while time.monotonic() < deadline:
            st = fleet.stats()
            live = [
                r for r in st["replicas"]
                if r is not None and r["state"] == "live"
            ]
            if len(live) == 2:
                break
            time.sleep(0.05)
        assert len(live) == 2, st["replicas"]
        rep0 = next(r for r in live if r["replica"] == 0)
        assert rep0["generation"] >= 1  # restarted
        assert rep0["devices"] == 2 and rep0["mesh"] == [2]
        rep1 = next(r for r in live if r["replica"] == 1)
        assert rep1["devices"] == 1 and rep1["mesh"] is None
    finally:
        fleet.close()

    events = obs.read_events(str(tmp_path), recursive=True)
    dead = [e for e in events if e["type"] == "fleet_replica_dead"]
    assert any(e["replica_id"] == 0 for e in dead)
    # exactly-once delivery of the original keys
    served = [
        e["key"] for e in events if e["type"] == "fleet_request"
    ]
    assert sorted(served) == sorted(f"k{i}" for i in range(10))
    # heartbeats carry the per-replica device count
    hb_dev = {
        e["replica_id"]: e.get("devices")
        for e in events
        if e["type"] == "fleet_heartbeat"
    }
    assert hb_dev.get(0) == 2 and hb_dev.get(1) == 1
    start = next(e for e in events if e["type"] == "fleet_start")
    assert start["replica_devices"] == [2, 1]
    assert start["total_devices"] == 3


@needs8
def test_mixed_fleet_disjoint_device_slices():
    """Two mesh replicas get disjoint device index slices; restarts
    would reuse the same slice (the allocation is per replica id)."""
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=2, tol=0.0, track_psnr=False)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(
            buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none",
        ),
        FleetConfig(
            replicas=3,
            replica_meshes=((2,), (2, 2), None),
            min_queue_depth=16, verbose="none",
        ),
    )
    try:
        assert fleet._replica_devices[0] == (0, 1)
        assert fleet._replica_devices[1] == (2, 3, 4, 5)
        assert fleet._replica_devices[2] is None
        assert fleet.total_devices == 2 + 4 + 1
        assert fleet.capacity_hint == 2 * 7
    finally:
        fleet.close()


@needs8
def test_fleet_resolves_env_mesh_once_with_disjoint_slices(
    monkeypatch,
):
    """CCSC_SERVE_MESH armed with mesh_shape=None: the FLEET resolves
    the knob once and hands each replica an explicit shape + a
    disjoint device slice — N engines each resolving the env default
    prefix themselves would overlap devices while the capacity math
    counted them as distinct hardware."""
    monkeypatch.setenv("CCSC_SERVE_MESH", "2")
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=2, tol=0.0, track_psnr=False)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg,
        ServeConfig(
            buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none",
        ),
        FleetConfig(replicas=2, min_queue_depth=16, verbose="none"),
    )
    try:
        assert fleet._replica_mesh == [(2,), (2,)]
        assert fleet._replica_devices == [(0, 1), (2, 3)]
        assert fleet.total_devices == 4
    finally:
        fleet.close()


@needs8
def test_fleet_refuses_meshes_the_pool_cannot_back_disjointly(
    monkeypatch,
):
    """More mesh devices than the pool holds: strict (default)
    refuses at construction — overlapping slices would let the
    admission ceiling credit devices that do not exist;
    CCSC_SERVE_MESH_STRICT=0 builds with overlap instead."""
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=2, tol=0.0, track_psnr=False)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    scfg = ServeConfig(
        buckets=((4, (12, 12)),), max_wait_ms=2.0, verbose="none",
    )
    with pytest.raises(CCSCInputError, match="disjoint"):
        ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=3,
                replica_meshes=((4,), (4,), (2,)),  # needs 10 of 8
                min_queue_depth=16, verbose="none",
            ),
        )
    monkeypatch.setenv("CCSC_SERVE_MESH_STRICT", "0")
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(
            replicas=2,
            replica_meshes=((4,), (2, 4)),  # needs 12 of 8
            min_queue_depth=16, verbose="none",
        ),
    )
    try:
        assert fleet._replica_devices == [(0, 1, 2, 3), None]
        x, m = _req(12)
        assert fleet.reconstruct(
            x * m, mask=m, timeout=180
        ).recon.shape == (12, 12)
    finally:
        fleet.close()


@needs8
def test_fleet_honors_operator_pinned_mesh_devices():
    """ServeConfig.mesh_devices is the operator's word on which
    silicon serves (e.g. steering off a colocated learner's
    devices): a 1-replica fleet slices from exactly that pool — a
    standalone engine honors the pin, so the fleet must too — and a
    fleet whose meshes the pinned pool cannot back disjointly is
    refused naming the pool."""
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=2, tol=0.0, track_psnr=False)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none",
        mesh_shape=(2,), mesh_devices=(4, 5),
    )
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(replicas=1, min_queue_depth=16, verbose="none"),
    )
    try:
        assert fleet._replica_devices == [(4, 5)]
    finally:
        fleet.close()
    with pytest.raises(CCSCInputError, match="pinned mesh_devices"):
        ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=2, min_queue_depth=16, verbose="none",
            ),
        )


def test_fleet_malformed_env_mesh_errors_instead_of_silent_single(
    monkeypatch,
):
    """A typo'd CCSC_SERVE_MESH must refuse fleet construction with
    the named error — never silently fall back to single-device
    replicas at a fraction of the intended capacity."""
    monkeypatch.setenv("CCSC_SERVE_MESH", "8,2")
    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=2, tol=0.0, track_psnr=False)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    with pytest.raises(CCSCInputError, match="mesh spec"):
        ServeFleet(
            d, ReconstructionProblem(geom), cfg,
            ServeConfig(
                buckets=((2, (12, 12)),), max_wait_ms=2.0,
                verbose="none",
            ),
            FleetConfig(replicas=2, min_queue_depth=16, verbose="none"),
        )


def test_fleetconfig_replica_meshes_validation():
    with pytest.raises(ValueError, match="replica_meshes"):
        FleetConfig(replicas=2, replica_meshes=((2,),))  # wrong len
    with pytest.raises(ValueError, match="not a tuple of axis"):
        FleetConfig(replicas=2, replica_meshes=(2, None))  # bare int
    with pytest.raises(ValueError, match="not a tuple of axis"):
        FleetConfig(replicas=1, replica_meshes=("2x2",))  # spec string
    with pytest.raises(ValueError, match="not a tuple of axis"):
        FleetConfig(replicas=1, replica_meshes=("12",))  # digit string
    f = FleetConfig(replicas=2, replica_meshes=([2, 2], None))
    assert f.replica_meshes == ((2, 2), None)


def test_bench_refuses_malformed_mesh_spec_before_any_work(
    monkeypatch,
):
    """A typo'd CCSC_SERVE_MESH fails the bench workload up front
    (user error), instead of silently recording mesh_skipped after
    the expensive baseline arms ran (environment shortage)."""
    from ccsc_code_iccv2017_tpu.serve.bench import run_serve_workload

    monkeypatch.setenv("CCSC_SERVE_MESH", "4x")
    with pytest.raises(ValueError, match="mesh spec"):
        run_serve_workload()


def test_fleet_serving_bound_device_scaling():
    """The admission math of a mixed fleet: each replica contributes
    its own serving_bound; an unmeasured replica is credited at the
    best measured PER-DEVICE rate times its own device count."""
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    # mesh replica (8 devices) measured at 80 it/s; single-device
    # replica unmeasured -> credited 10 it/s. slots=4, 20 it/request.
    b = perfmodel.fleet_serving_bound(
        [(80.0, 8), (0.0, 1)], iters_per_request=20, slots=4
    )
    assert b["measured"] == 1
    assert b["per_device_iters_per_sec"] == pytest.approx(10.0)
    assert b["requests_per_sec"] == pytest.approx(
        (80.0 * 4 / 20) + (10.0 * 4 / 20)
    )
    # nothing measured -> the caller keeps its static floor
    assert perfmodel.fleet_serving_bound(
        [(0.0, 8), (0.0, 1)], 20, 4
    ) == {"requests_per_sec": 0.0, "measured": 0}
    # all-single-device fleets reproduce N x serving_bound exactly
    b2 = perfmodel.fleet_serving_bound(
        [(300.0, 1), (300.0, 1)], iters_per_request=30, slots=4
    )
    assert b2["requests_per_sec"] == pytest.approx(
        2 * perfmodel.serving_bound(300.0, 30, 4)["requests_per_sec"]
    )


# ----------------------------------------------------- ledger + gate


def test_mesh_serve_record_is_its_own_ledger_configuration(
    tmp_path, monkeypatch,
):
    """append_serve_record with a mesh arm writes TWO rows — default
    and mesh — under different knob digests, so each accrues its own
    history; an injected 0.5x mesh record is judged a regression
    against the mesh key's band (the perf_gate exit-1 contract)."""
    from ccsc_code_iccv2017_tpu.analysis import ledger

    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("CCSC_PERF_LEDGER", path)
    base = {
        "chip": "cpu",
        "shape_key": "solve2d:k32:s7x7:sz64x64",
        "knobs": {"requests": 16, "slots": 4},
        "n_compiles": 3,
        "mesh": "4x2",
        "mesh_devices": 8,
    }
    for v_def, v_mesh in ((2.0, 7.9), (2.05, 8.1), (1.98, 8.0)):
        rec = dict(
            base,
            engine_requests_per_sec=v_def,
            mesh_requests_per_sec=v_mesh,
        )
        assert ledger.append_serve_record(rec) is not None
    rows = ledger.Ledger(path).read()
    assert len(rows) == 6
    keys = {ledger.record_key(r) for r in rows}
    assert len(keys) == 2  # default + mesh configurations
    mesh_rows = [
        r for r in rows if (r.get("knobs") or {}).get("mesh") == "4x2"
    ]
    assert len(mesh_rows) == 3
    assert all(
        (r.get("knobs") or {}).get("devices") == 8 for r in mesh_rows
    )
    # gate: an injected 0.5x record under the MESH key regresses...
    led = ledger.Ledger(path)
    bad = ledger.normalize_record(
        chip="cpu", kind="serve", workload="serve2d",
        shape_key=base["shape_key"],
        knobs=dict(base["knobs"], mesh="4x2", devices=8),
        value=4.0, unit="requests/sec",
    )
    verdicts = ledger.gate(led, record=bad)
    assert any(not v["ok"] for v in verdicts), verdicts
    # ...while the same absolute value under the key's own history is
    # fine for a record matching the band
    good = dict(bad, value=8.05)
    assert all(v["ok"] for v in ledger.gate(led, record=good))


# -------------------------------------------------- obs_report render


def _load_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(
            os.path.dirname(__file__), "..", "scripts", "obs_report.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_topology_and_flags_ceiling_mismatch():
    """SERVING renders the per-replica device shape; a mixed fleet
    whose live throughput exceeds the derived bound by >20% gets the
    CEILING MISMATCH flag (and an agreeing fleet does not)."""
    report = _load_report()

    def ev(t, type_, **f):
        return dict(f, t=t, type=type_)

    common = [
        ev(0.0, "run_meta", algorithm="serve_fleet"),
        ev(
            1.0, "serve_ready", replica_id=0, n_buckets=1,
            warmup_s=1.0, devices=4, mesh=[2, 2], knobs={},
        ),
        ev(
            1.1, "serve_ready", replica_id=1, n_buckets=1,
            warmup_s=1.0, devices=1, mesh=None, knobs={},
        ),
        ev(
            2.0, "serve_request", replica_id=0, trace_id="t1",
            bucket="4@24x24", latency_ms=10.0, iters=4, wait_ms=1.0,
        ),
    ]
    # bound 1 req/s but 10 requests in ~1 s -> mismatch
    fast = [
        ev(
            3.0, "fleet_ceiling", replica_id=None, ceiling=8,
            bound_requests_per_sec=1.0, source="serving_bound",
        ),
    ] + [
        ev(
            4.0 + 0.1 * i, "fleet_request", replica_id=0,
            trace_id=f"t{i}", key=f"k{i}", latency_ms=10.0,
        )
        for i in range(10)
    ]
    out = report.render(common + fast)
    assert "replica 0: 4 device(s)  mesh 2x2" in out
    assert "replica 1: 1 device(s)  single-device" in out
    assert "CEILING MISMATCH" in out
    # agreeing ceiling: no flag
    ok = [
        ev(
            3.0, "fleet_ceiling", replica_id=None, ceiling=8,
            bound_requests_per_sec=50.0, source="serving_bound",
        ),
    ] + fast[1:]
    out2 = report.render(common + ok)
    assert "CEILING MISMATCH" not in out2
    assert "replica 0: 4 device(s)" in out2
