"""Parity tests for the fused z-iteration Pallas kernel
(ops.pallas_fused_z; interpret mode on CPU — SURVEY.md section 4's
fake-backend strategy). The kernel fuses the entire z ADMM inner
iteration of the consensus learner (dzParallel.m:150-158)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
from ccsc_code_iccv2017_tpu.ops import freq_solvers, pallas_fused_z, proxes


def _problem(N=3, K=6, Sy=12, Sx=10, seed=0):
    rng = np.random.default_rng(seed)
    z = rng.standard_normal((N, K, Sy, Sx)).astype(np.float32)
    du = rng.standard_normal((N, K, Sy, Sx)).astype(np.float32)
    d = rng.standard_normal((K, Sy, Sx)).astype(np.float32)
    dhat = np.fft.rfftn(d, axes=(-2, -1)).astype(np.complex64)
    b = rng.standard_normal((N, Sy, Sx)).astype(np.float32)
    bhat = np.fft.rfftn(b, axes=(-2, -1)).astype(np.complex64)
    rho = 1.0
    minv = (1.0 / (1.0 + np.sum(np.abs(dhat) ** 2, 0) / rho)).astype(
        np.float32
    )
    return z, du, bhat, dhat, minv, rho


@pytest.mark.parametrize("Sy,Sx", [(12, 10), (9, 9)])
def test_fused_z_iter_matches_einsum_composition(Sy, Sx):
    """The kernel equals the exact prox/FFT/solve_z/iFFT composition it
    fuses — including odd transform lengths."""
    z, du, bhat, dhat, minv, rho = _problem(Sy=Sy, Sx=Sx)
    theta = 0.35
    N, K = z.shape[:2]
    Fx = Sx // 2 + 1
    zk, dk = pallas_fused_z.fused_z_iter(
        jnp.asarray(z), jnp.asarray(du), jnp.asarray(bhat),
        jnp.asarray(dhat), jnp.asarray(minv), rho, theta, interpret=True,
    )
    # composition via the production ops
    s = z + du
    u2 = np.asarray(proxes.soft_threshold(jnp.asarray(s), theta))
    dual_new = s - u2
    xi = 2 * u2 - s
    xihat = np.fft.rfftn(xi, axes=(-2, -1)).astype(np.complex64)
    zkern = freq_solvers.precompute_z_kernel(
        jnp.asarray(dhat.reshape(K, 1, -1)), rho
    )
    zhat = freq_solvers.solve_z(
        zkern,
        jnp.asarray(bhat.reshape(N, 1, -1)),
        jnp.asarray(xihat.reshape(N, K, -1)),
        rho,
    )
    z_ref = np.fft.irfftn(
        np.asarray(zhat).reshape(N, K, Sy, Fx), s=(Sy, Sx), axes=(-2, -1)
    )
    np.testing.assert_allclose(np.asarray(zk), z_ref, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dk), dual_new, atol=1e-6)


def test_fused_z_iter_bf16_state():
    """bf16 state round-trips with only storage rounding (math in f32)."""
    z, du, bhat, dhat, minv, rho = _problem()
    zk, dk = pallas_fused_z.fused_z_iter(
        jnp.asarray(z).astype(jnp.bfloat16),
        jnp.asarray(du).astype(jnp.bfloat16),
        jnp.asarray(bhat), jnp.asarray(dhat), jnp.asarray(minv),
        rho, 0.35, interpret=True,
    )
    assert zk.dtype == jnp.bfloat16 and dk.dtype == jnp.bfloat16
    zf, _ = pallas_fused_z.fused_z_iter_reference(
        jnp.asarray(z), jnp.asarray(du), jnp.asarray(bhat),
        jnp.asarray(dhat), jnp.asarray(minv), rho, 0.35,
    )
    err = float(jnp.abs(zk.astype(jnp.float32) - zf).max())
    scale = float(jnp.abs(zf).max())
    assert err < 0.02 * scale, (err, scale)


def test_high_precision_decomposition():
    """'high' is a hand-rolled 3-pass bf16 split (Mosaic rejects
    lax.Precision.HIGH in-kernel — r5 on-chip): hi*hi + hi*lo + lo*hi
    must sit within the ~1e-6 relative class of the f32 product, far
    tighter than single-pass bf16."""
    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((64, 48)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((48, 32)).astype(np.float32))
    exact = np.asarray(jnp.einsum(
        "yx,xv->yv", a, b, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ))
    scale = np.abs(exact).max()
    high = np.asarray(pallas_fused_z._make_ein("high")("yx,xv->yv", a, b))
    one = np.asarray(jnp.einsum(
        "yx,xv->yv", a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ))
    assert np.abs(high - exact).max() < 1e-5 * scale
    # sanity: the 3-pass split is far more accurate than 1-pass bf16
    assert np.abs(high - exact).max() < 0.01 * np.abs(one - exact).max()


def test_fused_z_iter_high_precision_close():
    """precision='high' keeps the whole fused iteration in the ~1e-4
    accuracy class vs the exact composition (the documented tier)."""
    z, du, bhat, dhat, minv, rho = _problem()
    zk, _ = pallas_fused_z.fused_z_iter(
        jnp.asarray(z), jnp.asarray(du), jnp.asarray(bhat),
        jnp.asarray(dhat), jnp.asarray(minv), rho, 0.35,
        interpret=True, precision="high",
    )
    zf, _ = pallas_fused_z.fused_z_iter_reference(
        jnp.asarray(z), jnp.asarray(du), jnp.asarray(bhat),
        jnp.asarray(dhat), jnp.asarray(minv), rho, 0.35,
    )
    err = float(jnp.abs(zk - zf).max())
    scale = float(jnp.abs(zf).max())
    assert err < 1e-3 * scale, (err, scale)


def test_learner_fused_z_matches_composition():
    """LearnConfig(fused_z=True) reproduces the default learner
    trajectory to float tolerance (interpret mode on CPU)."""
    rng = np.random.default_rng(4)
    b = jnp.asarray(rng.standard_normal((4, 12, 12)).astype(np.float32))
    geom = ProblemGeom((5, 5), 6)
    kw = dict(
        max_it=2, max_it_d=2, max_it_z=2, num_blocks=2,
        rho_d=500.0, rho_z=10.0, lambda_prior=0.5,
        verbose="none", track_objective=True,
    )
    r_ref = learn(b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(1))
    r_fus = learn(
        b, geom, LearnConfig(**kw, fused_z=True), key=jax.random.PRNGKey(1)
    )
    np.testing.assert_allclose(
        np.asarray(r_ref.d), np.asarray(r_fus.d), atol=2e-5
    )
    np.testing.assert_allclose(
        r_ref.trace["obj_vals_z"], r_fus.trace["obj_vals_z"], rtol=1e-5
    )


def test_fused_z_falls_back_when_unsupported():
    """W > 1 geometry silently takes the composition path (identical
    results, no error)."""
    rng = np.random.default_rng(5)
    b = jnp.asarray(rng.standard_normal((2, 3, 12, 12)).astype(np.float32))
    geom = ProblemGeom((5, 5), 4, (3,))
    kw = dict(
        max_it=1, max_it_d=1, max_it_z=2, num_blocks=1,
        verbose="none", track_objective=True,
    )
    r_ref = learn(b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(0))
    r_fus = learn(
        b, geom, LearnConfig(**kw, fused_z=True), key=jax.random.PRNGKey(0)
    )
    np.testing.assert_allclose(
        np.asarray(r_ref.d), np.asarray(r_fus.d), atol=1e-7
    )


def test_learner_fused_z_mesh_matches_local():
    """fused_z under a 4-device block mesh equals the unsharded run
    (off-TPU the sharded fused path routes through the identical-math
    jnp reference — pallas interpret mode cannot run under
    shard_map's vma checks; the mosaic lowering on real TPU can)."""
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    rng = np.random.default_rng(6)
    b = jnp.asarray(rng.standard_normal((8, 12, 12)).astype(np.float32))
    geom = ProblemGeom((5, 5), 6)
    kw = dict(
        max_it=2, max_it_d=2, max_it_z=2, num_blocks=4,
        verbose="none", track_objective=True,
    )
    r_local = learn(
        b, geom, LearnConfig(**kw, fused_z=True), key=jax.random.PRNGKey(0)
    )
    r_mesh = learn(
        b, geom, LearnConfig(**kw, fused_z=True), key=jax.random.PRNGKey(0),
        mesh=block_mesh(4),
    )
    np.testing.assert_allclose(
        np.asarray(r_local.d), np.asarray(r_mesh.d), atol=1e-5
    )
