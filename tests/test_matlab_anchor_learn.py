"""MATLAB-anchored golden trajectory for the CONSENSUS LEARNER
(VERDICT r3 next-round #5).

Like tests/test_matlab_anchor.py (inpainting), this file is a LITERAL,
line-ordered float64 NumPy transcription of the reference consensus
learner 2D/admm_learn_conv2D_large_dzParallel.m — full complex fft2,
column-major (order='F') per-frequency flattening, the exact MATLAB
init (:38-47, Dbar/Udbar zero :79-86), pinv-based Woodbury inverse
(:241), update order (global prox :107 -> dual :110 -> local solve
:112 -> consensus average :115-121), and rho constants (5000 at
:99,112; 1 at :154) — transcribed statement by statement rather than
re-derived. The framework learner shares no code or structure with it
(rfft half-spectra, einsum Woodbury over a real Cholesky embedding,
lax.scan inner loops).

Two DISCLOSED deviations from the literal text, both documented
divergences the framework also makes (models/learn.py docstring):
- objectiveFunction's residual sums over ALL blocks instead of only
  the loop-escaped last block (:320 evaluates b(:,:,(nn-1)*ni+1:nn*ni)
  with nn stuck at N — transcribing the bug would anchor to the bug);
- inner-loop tol breaks are elided (tests run tol=0, where the
  reference takes the same path).

The framework side runs with LearnConfig.compat_coding='block1' so it
codes/evaluates against dup{1} exactly as the reference does (:128,
:143, :166), and with the MATLAB init fed in verbatim (shared z across
blocks :44-47, Dbar=0) via a hand-built LearnState.

The same transcription parameterized at rho=500/50 with a GLOBAL z
array reproduces the dParallel variant (admm_learn_conv2D_large_
dParallel.m:45,85,143-160: z one array, theta=lambda/50 :150, rho=50
:153, coding dict fft2(D{1}) :143): test_dparallel_z_global_equals_
block_local proves the z-global and block-local-z trajectories are
IDENTICAL (the z-subproblem decomposes per image), which is the
evidence VERDICT r3 #9 asks for that component #1 (dParallel) is the
rho_d=500/rho_z=50 configuration of the unified learner.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
from ccsc_code_iccv2017_tpu.parallel import consensus


def fft2(x):
    return np.fft.fft2(x, axes=(0, 1))


def ifft2(x):
    return np.fft.ifft2(x, axes=(0, 1))


def kernel_constraint_proj(u, r):
    """KernelConstraintProj (:208-226): circshift to support, crop,
    project onto the unit ball where the norm exceeds 1, re-pad,
    shift back."""
    up = np.roll(u, (r, r), (0, 1))  # :215
    up = up[: 2 * r + 1, : 2 * r + 1, :]  # :216
    un = np.broadcast_to(
        np.sum(up**2, axis=(0, 1), keepdims=True), up.shape
    )  # :219
    up = np.where(
        un >= 1, up / np.sqrt(np.where(un >= 1, un, 1.0)), up
    )  # :220
    full = np.zeros_like(u)
    full[: 2 * r + 1, : 2 * r + 1, :] = up  # :223 padarray post
    return np.roll(full, (-r, -r), (0, 1))  # :224


def precompute_H_hat_D(z_hat, rho):
    """precompute_H_hat_D (:228-243): per-frequency A = [ni, k] code
    matrix and its pinv-based Woodbury inverse (:241)."""
    sx, sy, k, ni = z_hat.shape
    ss = sx * sy
    zf = np.reshape(z_hat, (ss, k, ni), order="F")  # :238 col-major
    Ainv = np.empty((ss, k, k), complex)
    for f in range(ss):
        A = zf[f].T  # [ni, k] (permute [3,2,1])
        Ainv[f] = (
            np.eye(k)
            - A.conj().T
            @ np.linalg.pinv(rho * np.eye(ni) + A @ A.conj().T)
            @ A
        ) / rho  # :241
    return zf, Ainv


def solve_conv_term_D(zf, Ainv, ud_hat, Bh, rho):
    """solve_conv_term_D (:258-281): x_f = Sinv (A' b + rho c)."""
    sx, sy, k = ud_hat.shape
    ss = sx * sy
    ni = Bh.shape[2]
    Bf = np.reshape(Bh, (ss, ni), order="F")  # :270
    cf = np.reshape(ud_hat, (ss, k), order="F")  # :271
    x = np.empty((ss, k), complex)
    for f in range(ss):
        A = zf[f].T
        x[f] = Ainv[f] @ (A.conj().T @ Bf[f] + rho * cf[f])  # :274
    return np.reshape(x, (sx, sy, k), order="F")  # :279


def precompute_H_hat_Z(dhat):
    """precompute_H_hat_Z (:245-256)."""
    sx, sy, k = dhat.shape
    dhat_flat = np.reshape(dhat, (sx * sy, k), order="F")  # :253
    dhatTdhat = np.sum(np.conj(dhat_flat) * dhat_flat, axis=1)  # :254
    return dhat_flat, dhatTdhat


def solve_conv_term_Z(dhat_flat, dhatTdhat, ud_hat, Bh, rho):
    """solve_conv_term_Z (:283-308): per-frequency Sherman-Morrison.
    dhatT(k,f) = conj(dhat_flat(f,k)) (:144/:303)."""
    sx, sy, k, ni = ud_hat.shape
    ss = sx * sy
    Bf = np.reshape(Bh, (ss, ni), order="F")
    zf = np.reshape(ud_hat, (ss, k, ni), order="F")
    bvec = (
        np.conj(dhat_flat)[:, :, None] * Bf[:, None, :] + rho * zf
    )  # :300
    corr = np.einsum("fk,fki->fi", dhat_flat, bvec)  # sum(conj(dhatT).*b)
    zh = (
        bvec / rho
        - (1.0 / (rho + dhatTdhat))[:, None, None]
        * np.conj(dhat_flat)[:, :, None]
        * corr[:, None, :]
        / rho
    )  # :303
    return np.reshape(zh, (sx, sy, k, ni), order="F")


def prox_sparse(u, theta):
    """ProxSparse = max(0, 1 - theta/|u|) .* u (:32)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
    return np.maximum(0.0, f) * u


def matlab_consensus_learner(
    b,
    d0_full,
    z0,
    N,
    r,
    rho_d,
    rho_z,
    lam_res,
    lam_pri,
    max_it,
    max_it_d,
    max_it_z,
    z_global=False,
):
    """Transcription of the dzParallel main loop (:90-194). With
    z_global=True the z-pass keeps one global array + dual like
    dParallel (:45,85,147-160); rho_d/rho_z parameterize the hardcoded
    5000/1 (dzParallel :99,112,154) vs 500/50 (dParallel :98,150,153);
    the sparsity threshold is lambda/rho_z (dzParallel theta=lambda at
    rho=1 :151; dParallel theta=lambda/50 at rho=50 :150).

    b: [H, W, n] unpadded; d0_full: [sx, sy, k] the :38-39 init
    (already embedded + circshifted); z0: [sx, sy, k, ni] the shared
    :44 init. Returns (obj_vals_d, obj_vals_z) of length max_it + 1.
    """
    H, W, n = b.shape
    ni = n // N
    sx, sy = H + 2 * r, W + 2 * r
    k = d0_full.shape[2]

    B = np.zeros((sx, sy, n))
    B[r : r + H, r : r + W, :] = b  # :23 padarray both
    B_hat = fft2(B)  # :24
    Bh = [B_hat[:, :, nn * ni : (nn + 1) * ni] for nn in range(N)]  # :26-28

    D = [d0_full.copy() for _ in range(N)]  # :40
    dup = [fft2(d0_full) for _ in range(N)]  # :41-42
    Z = [z0.copy() for _ in range(N)]  # :44-45
    Z_hat = [fft2(z0) for _ in range(N)]  # :46-47

    Dbar = np.zeros((sx, sy, k))  # :79
    Udbar = np.zeros((sx, sy, k))  # :80
    d_D = [np.zeros((sx, sy, k)) for _ in range(N)]  # :81
    d_Z = [np.zeros((sx, sy, k, ni)) for _ in range(N)]  # :84
    if z_global:  # dParallel: one z array + dual (:45,85)
        zg = np.concatenate(Z, axis=3)
        d_Zg = np.zeros((sx, sy, k, n))

    def objective(Zs, dup1):
        # objectiveFunction :310-331; residual over ALL blocks
        # (DISCLOSED deviation from the :320 last-block bug)
        f_z, g_z = 0.0, 0.0
        for nn in range(N):
            Dz = np.real(
                ifft2(np.sum(fft2(Zs[nn]) * dup1[:, :, :, None], axis=2))
            )  # :318
            crop = Dz[r : sx - r, r : sy - r, :]
            bb = b[:, :, nn * ni : (nn + 1) * ni]
            f_z += lam_res * 0.5 * np.sum((crop - bb) ** 2)  # :320 intent
            g_z += lam_pri * np.sum(np.abs(Zs[nn]))  # :324
        return f_z + g_z

    obj0 = objective(Z, dup[0])  # :56
    obj_vals_d, obj_vals_z = [obj0], [obj0]  # :69-70
    theta = lam_pri / rho_z  # :151 (dzP: lambda at rho 1; dP: lambda/50)

    for _ in range(max_it):  # :90
        # ---- D pass --------------------------------------------- :95-135
        pre = [precompute_H_hat_D(Z_hat[nn], rho_d) for nn in range(N)]  # :99
        for _i_d in range(max_it_d):  # :104
            u_D2 = kernel_constraint_proj(Dbar + Udbar, r)  # :107
            for nn in range(N):
                d_D[nn] = d_D[nn] + (D[nn] - u_D2)  # :110
                ud = fft2(u_D2 - d_D[nn])  # :111
                dup[nn] = solve_conv_term_D(
                    pre[nn][0], pre[nn][1], ud, Bh[nn], rho_d
                )  # :112
                D[nn] = np.real(ifft2(dup[nn]))  # :113
            Dbar = sum(D) / N  # :115-120
            Udbar = sum(d_D) / N  # :121
        obj_vals_d.append(objective(Z, dup[0]))  # :128 (last inner iter)

        # ---- Z pass -------------------------------------------- :140-172
        dhat_flat, dd = precompute_H_hat_Z(dup[0])  # :143
        for _i_z in range(max_it_z):  # :147
            if z_global:  # dParallel :147-160
                u = prox_sparse(zg + d_Zg, theta)  # :150
                d_Zg = d_Zg + (zg - u)  # :151
                ud = fft2(u - d_Zg)  # :152
                zh = solve_conv_term_Z(dhat_flat, dd, ud, B_hat, rho_z)  # :153
                zg = np.real(ifft2(zh))  # :154
            else:  # dzParallel :150-158
                for nn in range(N):
                    u = prox_sparse(Z[nn] + d_Z[nn], theta)  # :151
                    d_Z[nn] = d_Z[nn] + (Z[nn] - u)  # :152
                    ud = fft2(u - d_Z[nn])  # :153
                    Z_hat[nn] = solve_conv_term_Z(
                        dhat_flat, dd, ud, Bh[nn], rho_z
                    )  # :154
                    Z[nn] = np.real(ifft2(Z_hat[nn]))  # :155
        if z_global:
            Z = [zg[:, :, :, nn * ni : (nn + 1) * ni] for nn in range(N)]
            Z_hat = [fft2(zz) for zz in Z]
        obj_vals_z.append(objective(Z, dup[0]))  # :166

    return np.array(obj_vals_d), np.array(obj_vals_z)


def _problem(seed=21, H=8, s=3, k=4, n=4, N=2):
    """Shared tiny fixed-seed problem + the :38-47 init arrays."""
    rng = np.random.default_rng(seed)
    r = s // 2
    sx = H + 2 * r
    b = rng.uniform(0.1, 1.0, (H, H, n))
    d0 = rng.normal(size=(s, s, k))  # :38 randn(kernel_size)
    d0_full = np.zeros((sx, sx, k))
    d0_full[:s, :s, :] = d0  # :38 padarray post
    d0_full = np.roll(d0_full, (-r, -r), (0, 1))  # :39 circshift
    z0 = rng.normal(size=(sx, sx, k, n // N))  # :44 randn, shared :45
    return b, d0_full, z0, r


def _run_framework(b, d0_full, z0, N, cfg):
    """Drive the framework outer step from the MATLAB init verbatim:
    d_local = the :38-39 embedding on every block, z = the shared :44
    randn on every block, ALL duals AND Dbar/Udbar zero (:79-86; note
    init_state sets dbar=d_full instead — the anchor pins the
    reference's exact zero init)."""
    H, _, n = b.shape
    ni = n // N
    k = d0_full.shape[2]
    geom = ProblemGeom(
        (2 * (d0_full.shape[0] - H) // 2 + 1,) * 2, k
    )  # support (s, s)
    fg = common.FreqGeom.create(geom, (H, H))
    d_fw = jnp.asarray(np.moveaxis(d0_full, -1, 0), jnp.float32)  # [k,sx,sy]
    z_fw = jnp.asarray(
        np.broadcast_to(
            np.transpose(z0, (3, 2, 0, 1))[None], (N, ni, k, *fg.spatial_shape)
        ),
        jnp.float32,
    )
    state = learn_mod.LearnState(
        d_local=jnp.broadcast_to(d_fw, (N, *d_fw.shape)),
        dual_d=jnp.zeros((N, *d_fw.shape), jnp.float32),
        dbar=jnp.zeros_like(d_fw),
        udbar=jnp.zeros_like(d_fw),
        z=z_fw,
        dual_z=jnp.zeros_like(z_fw),
    )
    b_blocks = jnp.asarray(
        np.transpose(b, (2, 0, 1)).reshape(N, ni, H, H), jnp.float32
    )
    step = consensus.make_outer_step(geom, cfg, fg, mesh=None)
    obj_d, obj_z = [], []
    for _ in range(cfg.max_it):
        state, m = step(state, b_blocks)
        obj_d.append(float(m.obj_d))
        obj_z.append(float(m.obj_z))
    return np.array(obj_d), np.array(obj_z)


def test_learner_matches_matlab_transcription_dzparallel():
    """dzParallel operating point: rho 5000/1, max_it_d=5, max_it_z=10
    (:75-76,:99,:154). obj_d/obj_z trajectories must match the
    transcription to float32 tolerance."""
    b, d0_full, z0, r = _problem()
    N, max_it = 2, 3
    ml_d, ml_z = matlab_consensus_learner(
        b, d0_full, z0, N, r, 5000.0, 1.0, 1.0, 1.0, max_it, 5, 10
    )
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=max_it,
        tol=0.0,
        max_it_d=5,
        max_it_z=10,
        rho_d=5000.0,
        rho_z=1.0,
        num_blocks=N,
        verbose="none",
        track_objective=True,
        compat_coding="block1",
    )
    fw_d, fw_z = _run_framework(b, d0_full, z0, N, cfg)
    np.testing.assert_allclose(fw_d, ml_d[1:], rtol=2e-3)
    np.testing.assert_allclose(fw_z, ml_z[1:], rtol=2e-3)
    # trajectory must actually move (no trivial agreement)
    assert ml_z[-1] < 0.5 * ml_z[0]


def test_dparallel_z_global_equals_block_local():
    """dParallel's global z (:45,85) vs dzParallel's block-local z at
    the dParallel rho point 500/50: the z-subproblem decomposes per
    image, so the two bookkeeping schemes produce IDENTICAL
    trajectories — the unified learner's block-local z is dParallel's
    exact math at rho_d=500, rho_z=50 (VERDICT r3 #9 evidence)."""
    b, d0_full, z0, r = _problem(seed=33)
    N, max_it = 2, 2
    g_d, g_z = matlab_consensus_learner(
        b, d0_full, z0, N, r, 500.0, 50.0, 1.0, 1.0, max_it, 5, 10,
        z_global=True,
    )
    l_d, l_z = matlab_consensus_learner(
        b, d0_full, z0, N, r, 500.0, 50.0, 1.0, 1.0, max_it, 5, 10,
        z_global=False,
    )
    np.testing.assert_allclose(g_d, l_d, rtol=1e-12)
    np.testing.assert_allclose(g_z, l_z, rtol=1e-12)


def test_learner_matches_matlab_transcription_dparallel_point():
    """Framework at the dParallel config (rho 500/50) matches the
    transcription run z-globally — i.e. the framework IS the dParallel
    solver at this config."""
    b, d0_full, z0, r = _problem(seed=33)
    N, max_it = 2, 2
    ml_d, ml_z = matlab_consensus_learner(
        b, d0_full, z0, N, r, 500.0, 50.0, 1.0, 1.0, max_it, 5, 10,
        z_global=True,
    )
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=max_it,
        tol=0.0,
        max_it_d=5,
        max_it_z=10,
        rho_d=500.0,
        rho_z=50.0,
        num_blocks=N,
        verbose="none",
        track_objective=True,
        compat_coding="block1",
    )
    fw_d, fw_z = _run_framework(b, d0_full, z0, N, cfg)
    np.testing.assert_allclose(fw_d, ml_d[1:], rtol=2e-3)
    np.testing.assert_allclose(fw_z, ml_z[1:], rtol=2e-3)


def test_block1_compat_sharded_matches_unsharded():
    """compat_coding='block1' under a block mesh: block 1 lives on
    device 0, so the coding dictionary is psum-broadcast from there
    (models/learn.py outer_step); trajectories must equal the
    unsharded run."""
    b, d0_full, z0, r = _problem(seed=44)
    N = 2
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=2,
        tol=0.0,
        max_it_d=3,
        max_it_z=3,
        rho_d=5000.0,
        rho_z=1.0,
        num_blocks=N,
        verbose="none",
        track_objective=True,
        compat_coding="block1",
    )
    lo_d, lo_z = _run_framework(b, d0_full, z0, N, cfg)

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ccsc_code_iccv2017_tpu.parallel import mesh as mesh_lib

    H, _, n = b.shape
    ni = n // N
    k = d0_full.shape[2]
    geom = ProblemGeom((2 * r + 1,) * 2, k)
    fg = common.FreqGeom.create(geom, (H, H))
    mesh = mesh_lib.block_mesh(N)
    d_fw = jnp.asarray(np.moveaxis(d0_full, -1, 0), jnp.float32)
    z_fw = jnp.asarray(
        np.broadcast_to(
            np.transpose(z0, (3, 2, 0, 1))[None],
            (N, ni, k, *fg.spatial_shape),
        ),
        jnp.float32,
    )
    state = learn_mod.LearnState(
        d_local=jnp.broadcast_to(d_fw, (N, *d_fw.shape)),
        dual_d=jnp.zeros((N, *d_fw.shape), jnp.float32),
        dbar=jnp.zeros_like(d_fw),
        udbar=jnp.zeros_like(d_fw),
        z=z_fw,
        dual_z=jnp.zeros_like(z_fw),
    )
    specs = consensus._state_specs()
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        specs,
    )
    b_blocks = jax.device_put(
        jnp.asarray(
            np.transpose(b, (2, 0, 1)).reshape(N, ni, H, H), jnp.float32
        ),
        NamedSharding(mesh, P("block")),
    )
    step = consensus.make_outer_step(geom, cfg, fg, mesh)
    sh_d, sh_z = [], []
    for _ in range(cfg.max_it):
        state, m = step(state, b_blocks)
        sh_d.append(float(m.obj_d))
        sh_z.append(float(m.obj_z))
    np.testing.assert_allclose(sh_d, lo_d, rtol=2e-4)
    np.testing.assert_allclose(sh_z, lo_z, rtol=2e-4)
