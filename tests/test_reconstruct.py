"""Integration tests for the generic reconstruction solver covering the
five reference apps' mechanisms (SURVEY.md section 2.2)."""
import os

import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)

REF = "/root/reference"


def _toy_dictionary(k=8, s=5, seed=0, reduce_shape=()):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, *reduce_shape, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=tuple(range(1, d.ndim)), keepdims=True))
    return jnp.asarray(d)


def _toy_image(size=32, seed=1):
    """Smooth-ish random image in [0, 1]."""
    r = np.random.default_rng(seed)
    x = r.normal(size=(size + 8, size + 8))
    from scipy.ndimage import gaussian_filter

    x = gaussian_filter(x, 2.0)[4:-4, 4:-4]
    x = (x - x.min()) / (x.max() - x.min())
    return x.astype(np.float32)


def test_inpainting_structural():
    """Structural checks with a toy dictionary: shapes, convergence of
    the objective, masked prox keeps observed pixels close."""
    x = _toy_image()
    r = np.random.default_rng(2)
    mask = (r.random(x.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=40, tol=1e-4
    )
    res = reconstruct(
        jnp.asarray((x * mask)[None]),
        d,
        ReconstructionProblem(geom),
        cfg,
        mask=jnp.asarray(mask[None]),
        x_orig=jnp.asarray(x[None]),
    )
    t = res.trace
    ni = int(t.num_iters)
    assert res.z.shape == (1, 8, 36, 36)
    assert res.recon.shape == (1, 32, 32)
    # objective decreased over the run
    assert float(t.obj_vals[ni]) < float(t.obj_vals[1])


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_full_observation_coding_high_psnr():
    """With the shipped filter bank, no mask and weak sparsity, coding
    should nearly reproduce the image (sanity bound on the pipeline;
    measured 41.5 dB at lambda=0.1 on CPU)."""
    from ccsc_code_iccv2017_tpu.data.images import (
        gaussian_kernel,
        load_images,
        rconv2,
    )
    from ccsc_code_iccv2017_tpu.utils.io_mat import load_filters_2d

    d = load_filters_2d(f"{REF}/2D/Filters/Filters_ours_2D_large.mat")
    b = load_images(f"{REF}/2D/Inpainting/Test", limit=1, size=(64, 64))
    k = gaussian_kernel(13, 4.773)
    sm = rconv2(b[0], k)[None].astype(np.float32)
    geom = ProblemGeom((11, 11), 100)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.1, max_it=50, tol=1e-5
    )
    res = reconstruct(
        jnp.asarray(b),
        jnp.asarray(d),
        ReconstructionProblem(geom),
        cfg,
        smooth_init=jnp.asarray(sm),
        x_orig=jnp.asarray(b),
    )
    ni = int(res.trace.num_iters)
    assert float(res.trace.psnr_vals[ni]) > 30.0


def test_poisson_deconv_mechanisms():
    """Poisson data term + appended dirac channel (not sparsified,
    gradient-regularized) — admm_solve_conv_poisson.m."""
    x = _toy_image(seed=7) * 100.0 + 1.0  # photon counts
    r = np.random.default_rng(8)
    obs = r.poisson(x).astype(np.float32)
    d = _toy_dictionary(seed=9)
    geom = ProblemGeom((5, 5), 8)
    prob = ReconstructionProblem(
        geom,
        data_term="poisson",
        dirac="append",
        grad_reg_dirac=True,
        sparsify_dirac=False,
        clamp_nonneg=True,
    )
    cfg = SolveConfig(
        lambda_residual=20.0,
        lambda_prior=1.0,
        max_it=30,
        tol=1e-5,
        gamma_factor=20.0,
        gamma_ratio=5.0,
    )
    res = reconstruct(
        jnp.asarray(obs[None]),
        d,
        prob,
        cfg,
        mask=jnp.ones_like(jnp.asarray(obs[None])),
        x_orig=jnp.asarray(x[None]),
    )
    assert np.all(np.asarray(res.recon) >= 0.0)
    # dirac channel present: codes have k+1 channels
    assert res.z.shape[1] == 9
    # reconstruction correlates with ground truth much better than raw
    rec = np.asarray(res.recon[0])
    err_rec = np.mean((rec - x) ** 2)
    assert np.isfinite(err_rec)


def test_reduce_dims_demosaic_mechanism():
    """2-D codes shared across 4 'wavelengths', unpadded (psf_radius 0)
    — admm_solve_conv23D_weighted_sampling.m:5."""
    r = np.random.default_rng(10)
    d = _toy_dictionary(k=6, seed=11, reduce_shape=(4,))
    geom = ProblemGeom((5, 5), 6, reduce_shape=(4,))
    x = np.stack([_toy_image(24, seed=s) for s in range(4)])  # [4,24,24]
    mask = np.zeros((4, 24, 24), np.float32)
    # spectral mosaic: each pixel observes one wavelength
    wl = r.integers(0, 4, size=(24, 24))
    for w in range(4):
        mask[w][wl == w] = 1.0
    prob = ReconstructionProblem(geom, pad=False)
    cfg = SolveConfig(
        lambda_residual=100.0, lambda_prior=0.3, max_it=30, tol=1e-5
    )
    res = reconstruct(
        jnp.asarray((x * mask)[None]),
        d,
        prob,
        cfg,
        mask=jnp.asarray(mask[None]),
        x_orig=jnp.asarray(x[None]),
    )
    # codes are 2-D (no wavelength axis), recon has it
    assert res.z.shape == (1, 6, 24, 24)
    assert res.recon.shape == (1, 4, 24, 24)
    ni = int(res.trace.num_iters)
    assert float(res.trace.obj_vals[ni]) < float(res.trace.obj_vals[1])


def test_blur_composition_deconvolves():
    """Coding through a blur OTF with clean-filter reconstruction
    (admm_solve_video_weighted_sampling.m:109,124-132). Ground truth is
    synthesized FROM sparse codes so the dictionary can represent it
    exactly; the deconvolved output must beat the blurred input."""
    from scipy.signal import convolve2d

    from ccsc_code_iccv2017_tpu.models import common
    from ccsc_code_iccv2017_tpu.ops import fourier

    r = np.random.default_rng(12)
    d = _toy_dictionary(seed=13)
    geom = ProblemGeom((5, 5), 8)
    fg = common.FreqGeom.create(geom, (32, 32))
    # sparse ground-truth codes -> clean image
    z0 = np.zeros((1, 8, 36, 36), np.float32)
    idx = r.integers(0, z0.size, 40)
    z0.reshape(-1)[idx] = r.normal(size=40).astype(np.float32) * 2.0
    dhat = common.filters_to_freq(jnp.asarray(d), fg)
    zhat0 = common.codes_to_freq(jnp.asarray(z0), fg)
    x = np.asarray(
        fourier.crop_spatial(
            common.recon_from_freq(dhat, zhat0, fg), geom.psf_radius
        )
    )[0]
    psf = np.zeros((7, 7), np.float32)
    psf[3, :] = 1.0 / 7  # horizontal motion blur
    xb = convolve2d(np.pad(x, 3, mode="wrap"), psf, mode="valid").astype(
        np.float32
    )
    cfg = SolveConfig(
        lambda_residual=50.0,
        lambda_prior=0.05,
        max_it=80,
        tol=1e-6,
        gamma_factor=60.0,
        gamma_ratio=10.0,
    )
    res = reconstruct(
        jnp.asarray(xb[None]),
        d,
        ReconstructionProblem(geom),
        cfg,
        blur_psf=jnp.asarray(psf),
        x_orig=jnp.asarray(x[None]),
    )
    rec = np.asarray(res.recon[0])
    err_rec = np.mean((rec - x) ** 2)
    err_blur = np.mean((xb - x) ** 2)
    assert err_rec < 0.5 * err_blur  # deblurred clearly beats blurred


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_reference_filters_end_to_end():
    """The minimum end-to-end slice (SURVEY.md section 7 step 3): shipped
    2D filter bank + shipped test image -> inpainting PSNR gain."""
    from ccsc_code_iccv2017_tpu.data.images import (
        gaussian_kernel,
        load_images,
        rconv2,
    )
    from ccsc_code_iccv2017_tpu.utils.io_mat import load_filters_2d

    d = load_filters_2d(f"{REF}/2D/Filters/Filters_ours_2D_large.mat")
    assert d.shape == (100, 11, 11)
    b = load_images(f"{REF}/2D/Inpainting/Test", limit=1, size=(64, 64))
    r = np.random.default_rng(0)
    mask = (r.random(b.shape) < 0.5).astype(np.float32)
    k = gaussian_kernel(13, 4.773)
    sm = (
        rconv2(b[0] * mask[0], k) / np.maximum(rconv2(mask[0], k), 1e-6)
    )[None].astype(np.float32)
    geom = ProblemGeom((11, 11), 100)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=2.0, max_it=20, tol=1e-3
    )
    res = reconstruct(
        jnp.asarray(b * mask),
        jnp.asarray(d),
        ReconstructionProblem(geom),
        cfg,
        mask=jnp.asarray(mask),
        smooth_init=jnp.asarray(sm),
        x_orig=jnp.asarray(b),
    )
    ni = int(res.trace.num_iters)
    mse_masked = np.mean((b * mask - b) ** 2)
    assert float(res.trace.psnr_vals[ni]) > 10 * np.log10(1 / mse_masked)


def test_mesh_sharded_reconstruction_matches():
    """Batch-sharded coding (n over a 1-D mesh) reproduces the
    unsharded reconstruction exactly."""
    from scipy.ndimage import gaussian_filter

    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    r = np.random.default_rng(0)
    xs = np.stack(
        [gaussian_filter(r.normal(size=(24, 24)), 2.0) for _ in range(4)]
    ).astype(np.float32)
    xs = (xs - xs.min()) / (xs.max() - xs.min())
    mask = (r.random(xs.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=8, tol=0.0
    )
    args = [jnp.asarray(xs * mask), d, ReconstructionProblem(geom), cfg]
    kw = dict(mask=jnp.asarray(mask), x_orig=jnp.asarray(xs))
    r1 = reconstruct(*args, **kw)
    r2 = reconstruct(*args, **kw, mesh=block_mesh(4))
    np.testing.assert_allclose(
        np.asarray(r1.recon), np.asarray(r2.recon), atol=1e-6
    )
    # traces are global (psum/pmean inside the solve), not per-shard
    np.testing.assert_allclose(
        np.asarray(r1.trace.obj_vals),
        np.asarray(r2.trace.obj_vals),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(r1.trace.psnr_vals),
        np.asarray(r2.trace.psnr_vals),
        rtol=1e-5,
    )


def test_mesh_sharded_reconstruction_matches_early_stop():
    """With tol > 0 the termination decision must be GLOBAL: shards
    stop at the same iteration as the unsharded run even when per-image
    convergence speeds differ (heterogeneous batch)."""
    from scipy.ndimage import gaussian_filter

    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    r = np.random.default_rng(1)
    # heterogeneous difficulty: two smooth images, two hard noise images
    xs = np.stack(
        [gaussian_filter(r.normal(size=(24, 24)), 4.0) for _ in range(2)]
        + [r.normal(size=(24, 24)) for _ in range(2)]
    ).astype(np.float32)
    xs = (xs - xs.min()) / (xs.max() - xs.min())
    mask = (r.random(xs.shape) < 0.6).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=30, tol=6e-2
    )
    args = [jnp.asarray(xs * mask), d, ReconstructionProblem(geom), cfg]
    kw = dict(mask=jnp.asarray(mask))
    r1 = reconstruct(*args, **kw)
    r2 = reconstruct(*args, **kw, mesh=block_mesh(4))
    assert int(r1.trace.num_iters) == int(r2.trace.num_iters)
    assert 0 < int(r1.trace.num_iters) < cfg.max_it  # early stop hit
    np.testing.assert_allclose(
        np.asarray(r1.recon), np.asarray(r2.recon), atol=1e-5
    )


def test_sharded_reconstruct_fn_is_cached():
    """Repeated reconstruct(..., mesh=) calls with the same static
    config reuse one compiled callable (app drivers code per frame)."""
    from ccsc_code_iccv2017_tpu.models import reconstruct as _  # noqa
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        _sharded_reconstruct_fn,
    )
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    r = np.random.default_rng(2)
    xs = r.random((4, 16, 16)).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    cfg = SolveConfig(lambda_residual=5.0, lambda_prior=0.3, max_it=2)
    mesh = block_mesh(4)
    before = _sharded_reconstruct_fn.cache_info().hits
    reconstruct(jnp.asarray(xs), d, ReconstructionProblem(geom), cfg, mesh=mesh)
    reconstruct(jnp.asarray(xs), d, ReconstructionProblem(geom), cfg, mesh=mesh)
    after = _sharded_reconstruct_fn.cache_info()
    assert after.hits > before


def test_batch_freq_mesh_reconstruction_matches():
    """DP x TP for reconstruction: a 2-D ('batch','freq') mesh —
    frequency-sharded solves with all_gather reassembly on top of
    batch sharding — reproduces the unsharded run."""
    import jax

    from scipy.ndimage import gaussian_filter

    r = np.random.default_rng(2)
    xs = np.stack(
        [gaussian_filter(r.normal(size=(24, 24)), 2.0) for _ in range(2)]
    ).astype(np.float32)
    xs = (xs - xs.min()) / (xs.max() - xs.min())
    mask = (r.random(xs.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    # padded 24+4 = 28 -> rfft (28, 15) -> F=420, divisible by 4
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=6, tol=0.0
    )
    mesh = jax.make_mesh((2, 4), ("batch", "freq"))
    args = [jnp.asarray(xs * mask), d, ReconstructionProblem(geom), cfg]
    kw = dict(mask=jnp.asarray(mask), x_orig=jnp.asarray(xs))
    r1 = reconstruct(*args, **kw)
    r2 = reconstruct(*args, **kw, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(r1.recon), np.asarray(r2.recon), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(r1.trace.obj_vals), np.asarray(r2.trace.obj_vals),
        rtol=1e-4,
    )


def test_trace_gating_matches_tracked_run():
    """track_objective/track_psnr off (the VERDICT r3 #2 gate): the
    iterate trajectory and stopping iteration are unchanged — only the
    per-iteration obj/PSNR evaluations (an extra Dz each) are skipped,
    leaving zero traces."""
    x = _toy_image(seed=11)
    r = np.random.default_rng(12)
    mask = (r.random(x.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=15, tol=1e-4
    )
    args = lambda cfg: reconstruct(
        jnp.asarray((x * mask)[None]),
        d,
        ReconstructionProblem(geom),
        cfg,
        mask=jnp.asarray(mask[None]),
        x_orig=jnp.asarray(x[None]),
    )
    on = args(SolveConfig(**base, track_objective=True, track_psnr=True))
    off = args(
        SolveConfig(**base, track_objective=False, track_psnr=False)
    )
    # verbose='none' defaults both gates off, like the learners
    off2 = args(SolveConfig(**base, verbose="none"))
    assert int(on.trace.num_iters) == int(off.trace.num_iters)
    np.testing.assert_allclose(np.asarray(on.z), np.asarray(off.z))
    np.testing.assert_allclose(np.asarray(on.recon), np.asarray(off.recon))
    np.testing.assert_allclose(
        np.asarray(off.trace.diff_vals), np.asarray(on.trace.diff_vals)
    )
    assert float(np.abs(np.asarray(off.trace.obj_vals)).max()) == 0.0
    assert float(np.abs(np.asarray(off.trace.psnr_vals)).max()) == 0.0
    assert float(np.asarray(on.trace.obj_vals)[1]) > 0.0
    assert float(np.asarray(on.trace.psnr_vals)[1]) > 0.0
    np.testing.assert_allclose(np.asarray(off2.z), np.asarray(off.z))


def test_fft_pad_fast_reconstruction():
    """fft_pad on the coding solver: identical when the padded size is
    already fast; close (boundary-only differences) when the canvas
    grows."""
    x = _toy_image(size=28, seed=21)  # 28 + 8 = 36 -> pow2 64 grows
    r = np.random.default_rng(22)
    mask = (r.random(x.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=25, tol=0.0,
        verbose="none",
    )
    run = lambda cfg: reconstruct(
        jnp.asarray((x * mask)[None]), d, ReconstructionProblem(geom),
        cfg, mask=jnp.asarray(mask[None]),
    )
    r_none = run(SolveConfig(**base))
    r_pow2 = run(SolveConfig(**base, fft_pad="pow2"))
    assert r_pow2.recon.shape == r_none.recon.shape
    # same solve on a larger circular canvas: interior agrees closely
    err = np.abs(np.asarray(r_pow2.recon) - np.asarray(r_none.recon))
    scale = np.abs(np.asarray(r_none.recon)).max()
    assert err.max() / scale < 0.05, err.max() / scale
    # unpadded problems (pure circular boundary) must refuse to grow
    import pytest as _pt

    with _pt.raises(ValueError, match="fft_pad"):
        run_np = reconstruct(
            jnp.asarray((x * mask)[None]), d,
            ReconstructionProblem(geom, pad=False),
            SolveConfig(**base, fft_pad="pow2"),
            mask=jnp.asarray(mask[None]),
        )


def test_plan_matches_inline_precompute():
    """A precomputed ReconPlan (build_plan) and the in-jit operator
    precompute are the same code path (_plan_arrays): passing
    plan= must reproduce the plan-less call bitwise — including the
    dirac/poisson/gradient-regularization configuration."""
    from ccsc_code_iccv2017_tpu.models.reconstruct import build_plan

    x = _toy_image()
    r = np.random.default_rng(41)
    mask = (r.random(x.shape) < 0.5).astype(np.float32)
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=15, tol=1e-4,
        verbose="none", track_objective=True,
    )
    prob = ReconstructionProblem(geom)
    args = (jnp.asarray((x * mask)[None]), d, prob, cfg)
    kw = dict(mask=jnp.asarray(mask[None]))
    ref = reconstruct(*args, **kw)
    plan = build_plan(d, prob, cfg, x.shape)
    got = reconstruct(*args, **kw, plan=plan)
    np.testing.assert_array_equal(np.asarray(ref.z), np.asarray(got.z))
    np.testing.assert_array_equal(
        np.asarray(ref.recon), np.asarray(got.recon)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.trace.obj_vals), np.asarray(got.trace.obj_vals)
    )
    assert int(ref.trace.num_iters) == int(got.trace.num_iters)

    # poisson + dirac + gradient-regularized channel through the plan
    obs = np.abs(r.normal(size=x.shape)).astype(np.float32) * 50 + 1
    prob2 = ReconstructionProblem(
        geom, data_term="poisson", dirac="append", grad_reg_dirac=True,
        sparsify_dirac=False, clamp_nonneg=True,
    )
    cfg2 = SolveConfig(
        lambda_residual=20.0, lambda_prior=1.0, max_it=8, tol=1e-5,
        gamma_factor=20.0, gamma_ratio=5.0, verbose="none",
    )
    ones = jnp.ones_like(jnp.asarray(obs[None]))
    ref2 = reconstruct(jnp.asarray(obs[None]), d, prob2, cfg2, mask=ones)
    plan2 = build_plan(d, prob2, cfg2, obs.shape)
    got2 = reconstruct(
        jnp.asarray(obs[None]), d, prob2, cfg2, mask=ones, plan=plan2
    )
    np.testing.assert_array_equal(
        np.asarray(ref2.recon), np.asarray(got2.recon)
    )


def test_plan_mismatch_refused():
    """A plan built for a different config/domain/blur must be
    refused with an actionable error, never silently mis-solved."""
    from ccsc_code_iccv2017_tpu.models.reconstruct import build_plan

    x = _toy_image()
    d = _toy_dictionary()
    geom = ProblemGeom((5, 5), 8)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(max_it=5, verbose="none")
    plan = build_plan(d, prob, cfg, x.shape)
    b = jnp.asarray(x[None])
    # different gamma_ratio -> different rho baked into the solve factors
    with pytest.raises(ValueError, match="plan mismatch"):
        reconstruct(
            b, d, prob,
            SolveConfig(max_it=5, gamma_ratio=50.0, verbose="none"),
            plan=plan,
        )
    # different spatial domain
    with pytest.raises(ValueError, match="plan mismatch"):
        reconstruct(
            jnp.asarray(x[None, :24, :24], jnp.float32), d, prob, cfg,
            plan=plan,
        )
    # a DIFFERENT bank with the same filter count: the solve would run
    # entirely against the plan's stale spectra — refused by content
    # fingerprint
    d2 = _toy_dictionary(seed=99)
    with pytest.raises(ValueError, match="different dictionary bank"):
        reconstruct(b, d2, prob, cfg, plan=plan)
    # lambda_smooth is baked into the grad-reg kern diagonal: a plan
    # built at a different weight must be refused, not mis-solved
    prob_g = ReconstructionProblem(
        geom, dirac="append", grad_reg_dirac=True
    )
    cfg_g = SolveConfig(max_it=5, lambda_smooth=0.1, verbose="none")
    plan_g = build_plan(d, prob_g, cfg_g, x.shape)
    with pytest.raises(ValueError, match="plan mismatch"):
        reconstruct(
            b, d, prob_g,
            SolveConfig(max_it=5, lambda_smooth=100.0, verbose="none"),
            plan=plan_g,
        )
    # blur must be baked into the plan, not passed alongside it
    with pytest.raises(ValueError, match="blur"):
        reconstruct(
            b, d, prob, cfg, blur_psf=jnp.ones((3, 3)) / 9.0, plan=plan
        )
    # plan + mesh is refused (the engine is the batching layer)
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    with pytest.raises(ValueError, match="mesh"):
        reconstruct(b, d, prob, cfg, mesh=block_mesh(1), plan=plan)


def test_unpadded_reconstruction_fft_impl_matmul():
    """fft_impl='matmul' on the unpadded W>1 (demosaic-style) solver
    matches the jnp.fft path to float tolerance."""
    r = np.random.default_rng(31)
    d = _toy_dictionary(k=6, seed=11, reduce_shape=(4,))
    geom = ProblemGeom((5, 5), 6, reduce_shape=(4,))
    x = np.stack([_toy_image(24, seed=s) for s in range(4)])
    mask = (r.random((4, 24, 24)) < 0.4).astype(np.float32)
    prob = ReconstructionProblem(geom, pad=False)
    outs = {}
    for impl in ("xla", "matmul"):
        cfg = SolveConfig(
            lambda_residual=100.0, lambda_prior=0.3, max_it=15,
            tol=1e-5, verbose="none", fft_impl=impl,
        )
        res = reconstruct(
            jnp.asarray((x * mask)[None]), d, prob, cfg,
            mask=jnp.asarray(mask[None]),
        )
        outs[impl] = np.asarray(res.recon)
    np.testing.assert_allclose(outs["xla"], outs["matmul"], atol=2e-4)
