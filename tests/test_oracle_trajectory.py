"""Independent NumPy oracle of the consensus ADMM iteration.

The framework's outer step (models/learn.py::outer_step) re-derives the
reference's update order (2D/admm_learn_conv2D_large_dzParallel.m:90-194):
global kernel prox -> per-block dual update -> per-block frequency solve
-> consensus average for the d-pass; soft-threshold prox -> dual update
-> Sherman-Morrison solve for the z-pass. This oracle re-implements that
iteration from the math alone — full complex FFTs, dense per-frequency
``np.linalg.solve`` (no Woodbury/Sherman-Morrison/rfft tricks) and
explicit Python loops — and checks the jitted learner reproduces its
trajectory state-for-state over several outer iterations.

This is the integration-level counterpart of tests/test_ops.py's
per-solve dense checks: it pins the *composition* (update order, dual
bookkeeping, consensus averaging), which is where the reference's
convergence behavior lives (SURVEY.md section 7 "Hard parts").
"""
import numpy as np
import jax
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod


# ------------------------- NumPy oracle ------------------------------

def _circ_embed_np(psf, spatial_shape):
    ndim_s = len(spatial_shape)
    support = psf.shape[-ndim_s:]
    pad = [(0, 0)] * (psf.ndim - ndim_s) + [
        (0, full - s) for full, s in zip(spatial_shape, support)
    ]
    x = np.pad(psf, pad)
    shift = tuple(-(s // 2) for s in support)
    return np.roll(x, shift, axis=tuple(range(x.ndim - ndim_s, x.ndim)))


def _circ_extract_np(x, support):
    ndim_s = len(support)
    axes = tuple(range(x.ndim - ndim_s, x.ndim))
    rolled = np.roll(x, tuple(s // 2 for s in support), axis=axes)
    sl = [slice(None)] * (x.ndim - ndim_s) + [slice(0, s) for s in support]
    return rolled[tuple(sl)]


def _kernel_proj_np(d_full, support, spatial_shape):
    ndim_s = len(support)
    d_sup = _circ_extract_np(d_full, support)
    axes = tuple(range(d_sup.ndim - ndim_s, d_sup.ndim))
    sq = np.sum(d_sup * d_sup, axis=axes, keepdims=True)
    scale = np.where(sq >= 1.0, 1.0 / np.sqrt(np.maximum(sq, 1e-30)), 1.0)
    return _circ_embed_np(d_sup * scale, spatial_shape)


def _soft_np(u, theta):
    return np.sign(u) * np.maximum(np.abs(u) - theta, 0.0)


def oracle_outer_step(state, b_blocks, geom, cfg, spatial_shape):
    """One outer consensus iteration, dense NumPy, full complex FFTs."""
    L, ni = b_blocks.shape[:2]
    K = geom.num_filters
    support = geom.spatial_support
    radius = geom.psf_radius
    ndim_s = len(spatial_shape)
    fft_axes = tuple(range(-ndim_s, 0))
    F = int(np.prod(spatial_shape))

    d_local, dual_d, dbar, udbar, z, dual_z = [
        np.array(v, np.float64) for v in state
    ]

    pad = [(0, 0), (0, 0)] + [(r, r) for r in radius]
    b_pad = np.pad(b_blocks.astype(np.float64), pad)
    bhat = np.fft.fftn(b_pad, axes=fft_axes).reshape(L, ni, F)

    # ---- d-pass: Gram fixed at the incoming codes ----
    zhat = np.fft.fftn(z, axes=fft_axes).reshape(L, ni, K, F)

    for _ in range(cfg.max_it_d):
        u = _kernel_proj_np(dbar + udbar, support, spatial_shape)
        dual_d = dual_d + (d_local - u[None])
        xi = u[None] - dual_d
        xi_hat = np.fft.fftn(xi, axes=fft_axes).reshape(L, K, F)
        d_new_hat = np.empty_like(xi_hat)
        for l in range(L):
            for f in range(F):
                Z = zhat[l, :, :, f]  # [ni, K]
                A = cfg.rho_d * np.eye(K) + Z.conj().T @ Z
                rhs = Z.conj().T @ bhat[l, :, f] + cfg.rho_d * xi_hat[l, :, f]
                d_new_hat[l, :, f] = np.linalg.solve(A, rhs)
        d_local = np.real(
            np.fft.ifftn(
                d_new_hat.reshape(L, K, *spatial_shape), axes=fft_axes
            )
        )
        dbar = np.mean(d_local, axis=0)
        udbar = np.mean(dual_d, axis=0)

    # ---- z-pass: dictionary fixed at the projected consensus ----
    d_proj = _kernel_proj_np(dbar + udbar, support, spatial_shape)
    dhat = np.fft.fftn(d_proj, axes=fft_axes).reshape(K, F)
    theta = cfg.lambda_prior / cfg.rho_z

    for _ in range(cfg.max_it_z):
        u2 = _soft_np(z + dual_z, theta)
        dual_z = dual_z + (z - u2)
        xi2 = u2 - dual_z
        xi2_hat = np.fft.fftn(xi2, axes=fft_axes).reshape(L, ni, K, F)
        z_new_hat = np.empty_like(xi2_hat)
        for l in range(L):
            for n in range(ni):
                for f in range(F):
                    d = dhat[:, f]
                    A = cfg.rho_z * np.eye(K) + np.outer(d.conj(), d)
                    rhs = d.conj() * bhat[l, n, f] + cfg.rho_z * xi2_hat[l, n, :, f]
                    z_new_hat[l, n, :, f] = np.linalg.solve(A, rhs)
        z = np.real(
            np.fft.ifftn(
                z_new_hat.reshape(L, ni, K, *spatial_shape), axes=fft_axes
            )
        )

    return d_local, dual_d, dbar, udbar, z, dual_z


def test_outer_step_matches_numpy_oracle():
    geom = ProblemGeom((3, 3), 4)
    cfg = LearnConfig(
        max_it=3,
        max_it_d=2,
        max_it_z=2,
        num_blocks=2,
        rho_d=50.0,
        rho_z=2.0,
        lambda_residual=1.0,
        lambda_prior=1.0,
        verbose="none",
    )
    L, ni, size = 2, 2, 8
    fg = common.FreqGeom.create(geom, (size, size))

    b_blocks = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (L, ni, size, size)),
        np.float32,
    )
    state = learn_mod.init_state(jax.random.PRNGKey(0), geom, fg, L, ni)

    step = jax.jit(
        lambda s, b: learn_mod.outer_step(
            s, b, geom=geom, cfg=cfg, fg=fg, num_blocks=L, axis_name=None
        )
    )

    np_state = tuple(np.array(v, np.float64) for v in state)
    jx_state = state
    for it in range(cfg.max_it):
        np_state = oracle_outer_step(
            np_state, b_blocks, geom, cfg, fg.spatial_shape
        )
        jx_state, _ = step(jx_state, jnp.asarray(b_blocks))
        for name, a, b in zip(
            learn_mod.LearnState._fields, jx_state, np_state
        ):
            np.testing.assert_allclose(
                np.asarray(a, np.float64),
                b,
                atol=5e-4,
                rtol=5e-4,
                err_msg=f"outer iter {it}, field {name}",
            )
