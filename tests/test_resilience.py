"""Chaos tests for the resilience layer (utils.resilience,
utils.faults, hardened utils.checkpoint).

Every recovery path is proven end-to-end on CPU with deterministic
fault injection:

- kill/resume: a run SIGTERM'd at iteration k checkpoints cleanly and,
  resumed, matches the uninterrupted trajectory to float tolerance —
  INCLUDING dual variables — for all three learners (consensus,
  masked, streaming);
- divergence recovery: an injected NaN at iteration k triggers the
  rho-backoff retry (trace records it) and the run completes; with
  recovery disabled (default) the behavior is the historical
  stop-and-keep, byte-identical;
- checkpoint hardening: a corrupted newest snapshot falls back to the
  previous generation; a crash mid-save leaves the previous snapshot
  intact; a config-fingerprint mismatch refuses to resume;
- a SIGTERM'd subprocess exits with code 0 and a valid checkpoint;
- coordinator connect retries (parallel.distributed) and the
  Newton-Schulz condition guard (ops.freq_solvers).
"""
import os
import subprocess
import sys
from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked
from ccsc_code_iccv2017_tpu.parallel.streaming import learn_streaming
from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt
from ccsc_code_iccv2017_tpu.utils import faults


@pytest.fixture(autouse=True)
def _fault_isolation(monkeypatch):
    for v in (
        "CCSC_FAULT_NAN_IT",
        "CCSC_FAULT_CKPT_SAVE",
        "CCSC_FAULT_SIGTERM_IT",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


GEOM = ProblemGeom((3, 3), 4)


def _data(seed=1, n=4, side=12):
    return np.asarray(
        jax.random.normal(jax.random.PRNGKey(seed), (n, side, side)),
        np.float32,
    )


def _cfg(**kw):
    base = dict(
        max_it=4, max_it_d=2, max_it_z=2, num_blocks=2,
        rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
        track_objective=True,
    )
    base.update(kw)
    return LearnConfig(**base)


def _assert_state_matches(dir_a, dir_b, atol=2e-5):
    fa, ta, ia = ckpt.load(dir_a)
    fb, tb, ib = ckpt.load(dir_b)
    assert ia == ib
    assert sorted(fa) == sorted(fb)
    for k in fa:  # includes the dual variables
        np.testing.assert_allclose(
            np.asarray(fa[k], np.float32), np.asarray(fb[k], np.float32),
            atol=atol, err_msg=k,
        )
    for k in ("obj_vals_d", "obj_vals_z", "d_diff", "z_diff"):
        np.testing.assert_allclose(ta[k], tb[k], rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------- kill/resume


def test_consensus_kill_resume_matches(tmp_path, monkeypatch):
    b = jnp.asarray(_data())
    ck_full = str(tmp_path / "full")
    ck_kill = str(tmp_path / "kill")
    kw = dict(key=jax.random.PRNGKey(0), checkpoint_every=1)
    learn(b, GEOM, _cfg(), checkpoint_dir=ck_full, **kw)

    monkeypatch.setenv("CCSC_FAULT_SIGTERM_IT", "2")
    res = learn(b, GEOM, _cfg(), checkpoint_dir=ck_kill, **kw)
    assert res.trace.get("preemptions") == [2]
    _, _, it = ckpt.load(ck_kill)
    assert it == 2

    monkeypatch.delenv("CCSC_FAULT_SIGTERM_IT")
    faults.reset()
    learn(b, GEOM, _cfg(), checkpoint_dir=ck_kill, **kw)
    _assert_state_matches(ck_full, ck_kill)


def test_masked_kill_resume_matches(tmp_path, monkeypatch):
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 10, 10)).astype(np.float32))
    cfg = LearnConfig(max_it=4, max_it_d=2, max_it_z=2, tol=0.0,
                      verbose="none")
    kw = dict(gamma_div_d=50.0, gamma_div_z=10.0,
              key=jax.random.PRNGKey(0), checkpoint_every=1)
    ck_full = str(tmp_path / "full")
    ck_kill = str(tmp_path / "kill")
    learn_masked(b, geom, cfg, checkpoint_dir=ck_full, **kw)

    monkeypatch.setenv("CCSC_FAULT_SIGTERM_IT", "2")
    res = learn_masked(b, geom, cfg, checkpoint_dir=ck_kill, **kw)
    assert res.trace.get("preemptions") == [2]

    monkeypatch.delenv("CCSC_FAULT_SIGTERM_IT")
    faults.reset()
    learn_masked(b, geom, cfg, checkpoint_dir=ck_kill, **kw)
    _assert_state_matches(ck_full, ck_kill)


def test_streaming_kill_resume_matches(tmp_path, monkeypatch):
    b = _data()
    ck_full = str(tmp_path / "full")
    ck_kill = str(tmp_path / "kill")
    kw = dict(key=jax.random.PRNGKey(0), checkpoint_every=1)
    learn_streaming(b, GEOM, _cfg(), checkpoint_dir=ck_full, **kw)

    monkeypatch.setenv("CCSC_FAULT_SIGTERM_IT", "2")
    res = learn_streaming(b, GEOM, _cfg(), checkpoint_dir=ck_kill, **kw)
    assert res.trace.get("preemptions") == [2]

    monkeypatch.delenv("CCSC_FAULT_SIGTERM_IT")
    faults.reset()
    learn_streaming(b, GEOM, _cfg(), checkpoint_dir=ck_kill, **kw)
    _assert_state_matches(ck_full, ck_kill)


def test_sigterm_subprocess_clean_exit(tmp_path):
    """A real SIGTERM'd process: exit code 0 and a valid, resumable
    checkpoint at the iteration the signal landed on."""
    ck = str(tmp_path / "ck")
    code = f"""
import jax, jax.numpy as jnp, numpy as np
from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
b = jnp.asarray(np.asarray(
    jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32))
cfg = LearnConfig(max_it=4, max_it_d=2, max_it_z=2, num_blocks=2,
                  rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
                  track_objective=True)
learn(b, ProblemGeom((3, 3), 4), cfg, key=jax.random.PRNGKey(0),
      checkpoint_dir={ck!r}, checkpoint_every=1)
print("CLEAN_EXIT")
"""
    env = dict(os.environ, CCSC_FAULT_SIGTERM_IT="1", JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=240,
    )
    assert p.returncode == 0, p.stderr
    assert "CLEAN_EXIT" in p.stdout
    fields, trace, it = ckpt.load(ck)
    assert it == 1
    assert trace.get("preemptions") == [1]
    assert all(np.isfinite(np.asarray(v, np.float32)).all()
               for v in fields.values())


# ------------------------------------------------------- divergence recovery


def test_consensus_nan_recovery_per_step(monkeypatch):
    b = jnp.asarray(_data())
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn(b, GEOM, _cfg(max_recoveries=2), key=jax.random.PRNGKey(0))
    recs = res.trace["recoveries"]
    assert len(recs) == 1
    assert recs[0]["iteration"] == 2
    assert recs[0]["rho_scale"] == pytest.approx(0.5)
    # the run completed all 4 iterations despite the injected NaN
    assert len(res.trace["obj_vals_z"]) == 5
    assert np.isfinite(res.trace["obj_vals_z"]).all()
    assert np.isfinite(np.asarray(res.d)).all()


def test_consensus_nan_recovery_chunked_donated(monkeypatch):
    """Chunk-granular recovery at the readback fence, with donated
    state (the scan-carried last-good iterate is the restore point)."""
    b = jnp.asarray(_data())
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn(
        b, GEOM,
        _cfg(max_recoveries=2, outer_chunk=2, donate_state=True),
        key=jax.random.PRNGKey(0),
    )
    recs = res.trace["recoveries"]
    assert len(recs) == 1 and recs[0]["iteration"] == 2
    assert len(res.trace["obj_vals_z"]) == 5
    assert np.isfinite(res.trace["obj_vals_z"]).all()


def test_consensus_nan_disabled_keeps_last_good(monkeypatch):
    """Default (max_recoveries=0): stop-and-keep, byte-identical to a
    run truncated at the last good iteration."""
    b = jnp.asarray(_data())
    ref = learn(b, GEOM, _cfg(max_it=1), key=jax.random.PRNGKey(0))
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn(b, GEOM, _cfg(max_it=4), key=jax.random.PRNGKey(0))
    assert "recoveries" not in res.trace
    assert len(res.trace["obj_vals_z"]) == 2  # obj0 + iteration 1
    np.testing.assert_array_equal(np.asarray(res.d), np.asarray(ref.d))


def test_masked_nan_recovery_per_step(monkeypatch):
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 10, 10)).astype(np.float32))
    cfg = LearnConfig(max_it=4, max_it_d=2, max_it_z=2, tol=0.0,
                      verbose="none", max_recoveries=1)
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn_masked(
        b, geom, cfg, gamma_div_d=50.0, gamma_div_z=10.0,
        key=jax.random.PRNGKey(0),
    )
    recs = res.trace["recoveries"]
    assert len(recs) == 1 and recs[0]["iteration"] == 2
    assert len(res.trace["obj_vals_z"]) == 4
    assert np.isfinite(np.asarray(res.d)).all()


def test_masked_nan_recovery_chunked(monkeypatch):
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 10, 10)).astype(np.float32))
    cfg = LearnConfig(max_it=4, max_it_d=2, max_it_z=2, tol=0.0,
                      verbose="none", max_recoveries=1, outer_chunk=2)
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn_masked(
        b, geom, cfg, gamma_div_d=50.0, gamma_div_z=10.0,
        key=jax.random.PRNGKey(0),
    )
    recs = res.trace["recoveries"]
    assert len(recs) == 1 and recs[0]["iteration"] == 2
    assert len(res.trace["obj_vals_z"]) == 4
    assert np.isfinite(np.asarray(res.d)).all()


def test_streaming_nan_recovery(monkeypatch):
    b = _data()
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn_streaming(
        b, GEOM, _cfg(max_recoveries=1), key=jax.random.PRNGKey(0)
    )
    recs = res.trace["recoveries"]
    assert len(recs) == 1 and recs[0]["iteration"] == 2
    assert len(res.trace["obj_vals_z"]) == 5
    assert np.isfinite(res.trace["obj_vals_z"]).all()
    assert np.isfinite(res.Dz).all()


def test_streaming_nan_disabled_stops(tmp_path, monkeypatch):
    b = _data()
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    res = learn_streaming(b, GEOM, _cfg(), key=jax.random.PRNGKey(0),
                          checkpoint_dir=ck, checkpoint_every=1)
    assert "recoveries" not in res.trace
    # initial 0.0 entry + iteration 1; the poisoned chunk is dropped
    assert len(res.trace["obj_vals_z"]) == 2
    assert np.isfinite(res.trace["obj_vals_z"]).all()
    # the poisoned in-place state must NOT have reached the checkpoint:
    # the newest generation on disk is still the last good flush
    fields, trace, it = ckpt.load(ck)
    assert it == 1
    assert all(np.isfinite(np.asarray(v, np.float32)).all()
               for v in fields.values())


# ------------------------------------------------------ checkpoint hardening


St = namedtuple("St", ["a", "b"])


def test_checkpoint_corrupt_newest_falls_back(tmp_path):
    d = str(tmp_path)
    ckpt.save(d, St(np.ones(3), np.zeros(2)), {"x": [1]}, 1,
              fingerprint="fp")
    ckpt.save(d, St(np.full(3, 2.0), np.zeros(2)), {"x": [1, 2]}, 2,
              fingerprint="fp")
    fields, trace, it = ckpt.load(d, expect_fingerprint="fp")
    assert it == 2
    # tear the newest snapshot: load must warn and fall back to the
    # previous generation instead of crashing or restarting
    with open(os.path.join(d, "ccsc_state.npz"), "r+b") as fh:
        fh.truncate(10)
    with pytest.warns(UserWarning):
        fields, trace, it = ckpt.load(d, expect_fingerprint="fp")
    assert it == 1
    assert trace == {"x": [1]}
    np.testing.assert_array_equal(fields["a"], np.ones(3))
    # both generations corrupt -> explicit error, never a silent restart
    with open(os.path.join(d, "ccsc_state.prev.npz"), "r+b") as fh:
        fh.truncate(10)
    with pytest.warns(UserWarning):
        with pytest.raises(RuntimeError):
            ckpt.load(d, expect_fingerprint="fp")


def test_checkpoint_missing_trace_falls_back(tmp_path):
    """A state npz without its paired trace (crash between the state
    commit and the trace write) must not silently resume with a fresh
    trace while a complete previous generation exists — the recorded
    recoveries/history live in the trace."""
    d = str(tmp_path)
    ckpt.save(d, St(np.ones(3), np.zeros(2)), {"x": [1]}, 1)
    ckpt.save(d, St(np.full(3, 2.0), np.zeros(2)), {"x": [1, 2]}, 2)
    os.remove(os.path.join(d, "trace.json"))
    with pytest.warns(UserWarning):
        fields, trace, it = ckpt.load(d)
    assert it == 1
    assert trace == {"x": [1]}
    # no complete generation anywhere: degraded state-only resume of
    # the newest snapshot beats losing the iterate
    os.remove(os.path.join(d, "trace.prev.json"))
    with pytest.warns(UserWarning):
        fields, trace, it = ckpt.load(d)
    assert it == 2
    assert trace is None


def test_checkpoint_sha_detects_silent_corruption(tmp_path):
    d = str(tmp_path)
    ckpt.save(d, St(np.ones(3), np.zeros(2)), {"x": [1]}, 1)
    ckpt.save(d, St(np.full(3, 2.0), np.zeros(2)), {"x": [1, 2]}, 2)
    # overwrite the newest with a VALID npz that doesn't match its
    # sha256 sidecar — np.load would succeed, the hash must not
    valid_other = os.path.join(d, "other.npz")
    np.savez(valid_other, a=np.zeros(3), b=np.zeros(2),
             __iteration__=np.asarray(9))
    os.replace(valid_other, os.path.join(d, "ccsc_state.npz"))
    with pytest.warns(UserWarning):
        fields, trace, it = ckpt.load(d)
    assert it == 1


def test_checkpoint_save_crash_preserves_previous(tmp_path, monkeypatch):
    d = str(tmp_path)
    ckpt.save(d, St(np.ones(3), np.zeros(2)), {"x": [1]}, 1,
              fingerprint="fp")
    monkeypatch.setenv("CCSC_FAULT_CKPT_SAVE", "1")
    with pytest.raises(faults.InjectedFault):
        ckpt.save(d, St(np.full(3, 9.0), np.zeros(2)), {"x": [1, 2]}, 2,
                  fingerprint="fp")
    fields, trace, it = ckpt.load(d, expect_fingerprint="fp")
    assert it == 1
    assert trace == {"x": [1]}
    np.testing.assert_array_equal(fields["a"], np.ones(3))


def test_checkpoint_fingerprint_mismatch_refuses(tmp_path):
    d = str(tmp_path)
    ckpt.save(d, St(np.ones(3), np.zeros(2)), {"x": [1]}, 1,
              fingerprint="aaa")
    with pytest.raises(ValueError, match="different run"):
        ckpt.load(d, expect_fingerprint="bbb")
    # no expectation (legacy caller) or no stored fingerprint: accepted
    assert ckpt.load(d) is not None


def test_learner_refuses_mismatched_checkpoint(tmp_path):
    b = jnp.asarray(_data())
    ck = str(tmp_path / "ck")
    learn(b, GEOM, _cfg(max_it=2), key=jax.random.PRNGKey(0),
          checkpoint_dir=ck, checkpoint_every=1)
    with pytest.raises(ValueError, match="different run"):
        learn(b, GEOM, _cfg(max_it=2, lambda_prior=0.7),
              key=jax.random.PRNGKey(0), checkpoint_dir=ck)


# --------------------------------------------------------------- satellites


def test_distributed_initialize_retries(monkeypatch):
    import time

    from ccsc_code_iccv2017_tpu.parallel import distributed

    calls = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("connection refused")

    sleeps = []
    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setattr(
        distributed, "_runtime_already_initialized", lambda: False
    )
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    distributed.initialize(
        coordinator_address="127.0.0.1:1", num_processes=2, process_id=0,
        connect_retries=5, connect_backoff=0.25,
    )
    assert len(calls) == 3
    assert sleeps == [0.25, 0.5]
    # exhausted budget re-raises
    calls.clear()
    monkeypatch.setattr(distributed, "_initialized", False)

    def always_fails(**kw):
        calls.append(kw)
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", always_fails)
    with pytest.raises(RuntimeError, match="connection refused"):
        distributed.initialize(
            coordinator_address="127.0.0.1:1", num_processes=2,
            process_id=0, connect_retries=2, connect_backoff=0.0,
        )
    assert len(calls) == 3


def test_newton_cond_guard_falls_back():
    from ccsc_code_iccv2017_tpu.ops import freq_solvers as fs

    rng = np.random.default_rng(0)

    def make(cond, m=8, batch=3):
        q, _ = np.linalg.qr(
            rng.normal(size=(batch, m, m))
            + 1j * rng.normal(size=(batch, m, m))
        )
        lam = np.stack([np.logspace(0, np.log10(cond), m)] * batch)
        G = (q * lam[:, None, :]) @ np.conj(np.swapaxes(q, -1, -2))
        return jnp.asarray(G, jnp.complex64)

    # inside the validity window: stays on the Newton iterate (close
    # to, but not bitwise, the direct inverse)
    G = make(10.0)
    ref = fs.hermitian_inverse(G, method="cholesky")
    out = fs.hermitian_inverse(G, method="newton")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=0, atol=1e-5)
    # far outside: the guard swaps in the direct inverse wholesale
    G = make(1e7)
    ref = fs.hermitian_inverse(G, method="cholesky")
    out = fs.hermitian_inverse(G, method="newton")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_chaos_smoke_script():
    """The CI chaos harness itself: one representative scenario per
    fault point (the dedicated tests above cover every variant — the
    script run proves its own plumbing without re-paying each jit
    compile twice)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import chaos_smoke
    finally:
        sys.path.pop(0)
    results = chaos_smoke.run(
        subprocess_scenarios=False,
        only=("nan_recovery", "ckpt_save_crash", "corrupt_fallback",
              "sigterm_checkpoint"),
    )
    assert len(results) == 4
    assert all(ok for ok, _ in results.values()), results
