"""The static analysis suite (ccsc_code_iccv2017_tpu/analysis) —
fixture-pinned analyzer behavior, baseline mechanics, and the tier-1
gate that runs every check over the real tree.

Layout:
- per-check fixture tests: known-bad snippets under
  tests/fixtures/analysis/ must fire with the EXACT check id and
  line; known-clean snippets (idiomatic patterns from the real
  drivers) must stay silent;
- framework tests: inline suppressions, baseline multiset matching,
  stale-baseline detection;
- the gate: all checks over ccsc_code_iccv2017_tpu/ + scripts/ under
  the reviewed baseline, in under 30 s; stale baseline entries fail;
  docs/ENV_KNOBS.md must match the utils.env registry;
- the scripts/lint.py CLI: exit codes, --json, --update-baseline.
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.analysis import core, envreg  # noqa: E402

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")
PKGTREE = os.path.join(FIX, "pkgtree")


def run_on(path, check, repo_root=FIX):
    project = core.Project([os.path.join(FIX, path)], repo_root=repo_root)
    return core.run_checks(project, [check])


def hits(findings, check):
    return [(f.line, f.message) for f in findings if f.check == check]


# ---------------------------------------------------------------- jit-purity


def test_jit_purity_fires_on_known_bad():
    fs = run_on("purity_bad.py", "jit-purity")
    lines = sorted(f.line for f in fs)
    assert all(f.check == "jit-purity" for f in fs)
    # hot_step: clock, .item(), traced branch, print, env read;
    # helper (reachable): np.asarray; scan body: clock
    assert lines == [13, 14, 15, 17, 18, 24, 28], [
        (f.line, f.message) for f in fs
    ]
    msgs = {f.line: f.message for f in fs}
    assert "host clock read" in msgs[13]
    assert ".item()" in msgs[14]
    assert "branch on a traced value" in msgs[15]
    assert "env read" in msgs[18]
    assert "numpy materialization" in msgs[24]


def test_jit_purity_silent_on_clean_and_suppressed():
    assert run_on("purity_clean.py", "jit-purity") == []


# ------------------------------------------------------------ donation-safety


def test_donation_safety_fires_on_known_bad():
    fs = run_on("donation_bad.py", "donation-safety")
    assert sorted(f.line for f in fs) == [12, 19], [
        (f.line, f.message) for f in fs
    ]
    assert all(f.check == "donation-safety" for f in fs)
    assert all("donated" in f.message for f in fs)


def test_donation_safety_silent_on_rebind_pattern():
    assert run_on("donation_clean.py", "donation-safety") == []


# -------------------------------------------------------------- thread-safety


def test_thread_safety_fires_on_known_bad():
    fs = run_on("threads_bad.py", "thread-safety")
    lines = sorted(f.line for f in fs)
    assert lines == [11, 28, 34, 37], [
        (f.line, f.message) for f in fs
    ]
    msgs = {f.line: f.message for f in fs}
    assert "inconsistent lock order" in msgs[11]
    assert "obs emission" in msgs[28]
    assert "time.sleep" in msgs[34]
    assert "no join path" in msgs[37]


def test_thread_safety_silent_on_clean():
    assert run_on("threads_clean.py", "thread-safety") == []


# ----------------------------------------------------------------- obs-schema


def test_obs_schema_fires_on_known_bad():
    fs = run_on("events_bad.py", "obs-schema")
    lines = sorted(f.line for f in fs)
    assert lines == [5, 6, 12, 16], [(f.line, f.message) for f in fs]
    msgs = {f.line: f.message for f in fs}
    assert "without required field" in msgs[5]
    assert "undeclared obs event `totally_new_event`" in msgs[6]
    assert "undeclared obs event `bogus_record`" in msgs[12]
    assert "consumer reads undeclared" in msgs[16]


def test_obs_schema_silent_on_clean():
    assert run_on("events_clean.py", "obs-schema") == []


def test_obs_schema_span_pairing_fires_on_end_only():
    """Span convention (ISSUE 9): a span_end emitted for a literal
    span name with no span_start emitter anywhere in the project is
    an orphan by construction."""
    fs = run_on("events_span_bad.py", "obs-schema")
    assert len(fs) == 1, [(f.line, f.message) for f in fs]
    assert fs[0].check == "obs-schema"
    assert "no span_start emitter" in fs[0].message
    assert "orphan_phase" in fs[0].message


def test_obs_schema_span_pairing_silent_on_paired():
    assert run_on("events_span_clean.py", "obs-schema") == []


def test_obs_schema_registry_span_conventions():
    """Registry-side conventions: span_* events must require the full
    trace context, serve_*/fleet_* must require replica_id, and a
    declared span_end implies a declared span_start — and the REAL
    registry satisfies all three."""
    from ccsc_code_iccv2017_tpu.analysis import events as ev

    bad = {
        "span_end": frozenset({"trace_id"}),
        "fleet_thing": frozenset(),
        "span_start": frozenset(
            {"trace_id", "span", "span_id", "replica_id"}
        ),
    }
    msgs = [f.message for f in ev.registry_findings(bad)]
    assert any(
        "span event `span_end` must require" in m for m in msgs
    )
    assert any("serving event `fleet_thing`" in m for m in msgs)
    end_only = {
        "span_end": frozenset(
            {"trace_id", "span", "span_id", "replica_id", "status"}
        )
    }
    msgs2 = [f.message for f in ev.registry_findings(end_only)]
    assert any("without `span_start`" in m for m in msgs2)
    assert ev.registry_findings() == []  # the shipped registry is clean


# --------------------------------------------------------------- env-registry


def test_env_registry_fires_on_known_bad():
    fs = run_on("envreg_bad.py", "env-registry")
    lines = sorted(f.line for f in fs)
    assert lines == [6, 7, 14, 20], [(f.line, f.message) for f in fs]
    msgs = {f.line: f.message for f in fs}
    assert "raw env read of `CCSC_SOME_RAW_KNOB`" in msgs[6]
    assert "raw env read of `CCSC_RAW_SUBSCRIPT`" in msgs[7]
    assert "raw env read of `CCSC_ALIASED_RAW`" in msgs[14]
    assert "not declared in its REGISTRY" in msgs[20]


def test_env_registry_silent_on_clean():
    assert run_on("envreg_clean.py", "env-registry") == []


# ---------------------------------------------------- migrated conventions


def _pkgtree_project():
    return core.Project(
        [os.path.join(PKGTREE, "ccsc_code_iccv2017_tpu")],
        repo_root=PKGTREE,
    )


def test_bare_print_fires_in_library_not_apps():
    fs = core.run_checks(_pkgtree_project(), ["bare-print"])
    assert [(f.path, f.line) for f in fs] == [
        ("ccsc_code_iccv2017_tpu/utils/helper.py", 5)
    ]


def test_validate_routing_flags_boundary_skipping_app():
    fs = core.run_checks(_pkgtree_project(), ["validate-routing"])
    assert [f.path for f in fs] == [
        "ccsc_code_iccv2017_tpu/apps/badapp.py"
    ]
    assert "does not import utils.validate" in fs[0].message


def test_emit_routing_flags_direct_event():
    fs = core.run_checks(_pkgtree_project(), ["emit-routing"])
    assert [(f.path, f.line) for f in fs] == [
        ("ccsc_code_iccv2017_tpu/serve/engine.py", 17)
    ]
    assert "outside `_emit`" in fs[0].message


# ------------------------------------------------------- framework mechanics


def test_inline_suppression_applies_to_own_and_next_line(tmp_path):
    p = tmp_path / "s.py"
    p.write_text(
        "import os\n"
        "a = os.environ.get('CCSC_X')  # ccsc: allow[env-registry]\n"
        "# ccsc: allow[env-registry]\n"
        "b = os.environ.get('CCSC_Y')\n"
        "c = os.environ.get('CCSC_Z')\n"
    )
    project = core.Project([str(p)], repo_root=str(tmp_path))
    fs = core.run_checks(project, ["env-registry"])
    assert [f.line for f in fs] == [5]


def test_baseline_multiset_matching_and_stale():
    f1 = core.Finding("c", "p.py", 3, "msg one")
    f2 = core.Finding("c", "p.py", 9, "msg one")  # same key, new line
    base = [{"check": "c", "path": "p.py", "message": "msg one"},
            {"check": "c", "path": "p.py", "message": "gone"}]
    new, matched, stale = core.split_baseline([f1, f2], base)
    # one entry absorbs exactly one finding; the duplicate is NEW
    assert len(matched) == 1 and len(new) == 1
    assert stale == [base[1]]


def test_parse_error_is_its_own_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    project = core.Project([str(p)], repo_root=str(tmp_path))
    fs = core.run_checks(project, ["bare-print"])
    assert [f.check for f in fs] == ["parse"]


# ------------------------------------------------------------------ the gate


_REAL_TREE_CACHE = {}


def _real_tree():
    # one parse+analyze pass shared by the gate tests (the suite runs
    # in seconds, but there is no reason to pay it twice)
    if "r" not in _REAL_TREE_CACHE:
        project = core.Project(
            core.DEFAULT_ROOTS, repo_root=core.REPO_ROOT
        )
        findings = core.run_checks(project)
        baseline = core.load_baseline()
        _REAL_TREE_CACHE["r"] = core.split_baseline(findings, baseline)
    return _REAL_TREE_CACHE["r"]


def test_full_tree_is_clean_under_baseline():
    """THE tier-1 gate: every analyzer over the package + scripts/,
    zero findings outside the reviewed baseline, in under 30 s."""
    t0 = time.perf_counter()
    new, _matched, _stale = _real_tree()
    dt = time.perf_counter() - t0
    assert not new, "new static-analysis findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert dt < 30.0, f"lint suite took {dt:.1f}s (budget 30s)"


def test_baseline_entries_all_resolve():
    """Stale-baseline guard: every reviewed baseline entry must still
    match a real finding at a real location — fixed debt leaves the
    baseline, it does not rot in it."""
    _new, _matched, stale = _real_tree()
    assert not stale, (
        "stale baseline entries (fix was shipped — prune with "
        "`python scripts/lint.py --update-baseline`):\n"
        + "\n".join(json.dumps(e) for e in stale)
    )


def test_env_knobs_docs_are_fresh():
    """docs/ENV_KNOBS.md is generated from utils.env.REGISTRY —
    regenerate with `python scripts/lint.py --write-env-docs`."""
    path = os.path.join(REPO, "docs", "ENV_KNOBS.md")
    assert os.path.exists(path), (
        "docs/ENV_KNOBS.md missing — run "
        "`python scripts/lint.py --write-env-docs`"
    )
    with open(path, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == envreg.render_env_docs(), (
        "docs/ENV_KNOBS.md is stale vs utils.env.REGISTRY — run "
        "`python scripts/lint.py --write-env-docs`"
    )


def test_obs_schema_covers_every_emitted_event():
    """Belt-and-braces inverse of the gate: the registry declares at
    least the events the real tree emits (an event deleted from the
    registry while still emitted must fail here via the gate; an
    event never emitted anywhere AND never consumed is legal — e.g.
    reserved types)."""
    from ccsc_code_iccv2017_tpu.analysis.obs_schema import EVENT_SCHEMA

    assert "run_meta" in EVENT_SCHEMA and "summary" in EVENT_SCHEMA
    assert all(
        isinstance(v, frozenset) for v in EVENT_SCHEMA.values()
    )


# ------------------------------------------------------------------- the CLI


def _lint(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=120,
    )


def test_cli_exits_nonzero_on_new_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\nx = os.environ.get('CCSC_CLI_RAW')\n"
    )
    r = _lint(str(bad), "--checks", "env-registry",
              "--baseline", str(tmp_path / "none.json"))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "CCSC_CLI_RAW" in r.stdout


def test_cli_json_and_update_baseline_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\nx = os.environ.get('CCSC_CLI_RAW2')\n"
    )
    base = tmp_path / "baseline.json"
    r = _lint(str(bad), "--checks", "env-registry",
              "--baseline", str(base), "--update-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    # absorbed: the same tree now exits 0, finding reported baselined
    r2 = _lint(str(bad), "--checks", "env-registry",
               "--baseline", str(base), "--json")
    assert r2.returncode == 0, r2.stdout + r2.stderr
    out = json.loads(r2.stdout)
    assert out["new"] == [] and len(out["baselined"]) == 1
    # fix the file -> the baseline entry goes stale (reported, rc 0)
    bad.write_text("x = 1\n")
    r3 = _lint(str(bad), "--checks", "env-registry",
               "--baseline", str(base), "--json")
    assert r3.returncode == 0
    out3 = json.loads(r3.stdout)
    assert len(out3["stale_baseline"]) == 1


def test_cli_runs_the_shipped_tree_clean():
    """Acceptance: `python scripts/lint.py` exits 0 on the shipped
    tree (all five analyzers + the three convention checks, package
    + scripts, under the reviewed baseline)."""
    r = _lint()
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_list_names_all_checks():
    r = _lint("--list")
    names = set(r.stdout.split())
    assert {
        "jit-purity", "donation-safety", "thread-safety",
        "obs-schema", "env-registry", "bare-print", "emit-routing",
        "validate-routing",
    } <= names
