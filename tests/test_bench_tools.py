"""Units for the measurement tooling around bench.py (no TPU needed):

- bench.last_onchip_record — the degraded-fallback annotation that
  keeps rounds comparable when the tunnel is down at snapshot time
  (VERDICT r4 weak #2): picks the newest real-chip record, skips
  DEGRADED/zero rows, reports source + age.
- scripts/pick_tuned.py — knob selection must only ever see the
  NEWEST round's records (older rounds ran older code on an older
  tunnel) and must fall back to defaults when the baseline wins.
"""
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _rec(run, value, chip=True, knobs=None, degraded=False):
    suffix = ", DEGRADED: TPU unreachable, ran on cpu" if degraded else (
        ", 1 chip" if chip else ", cpu"
    )
    return {
        "run": run,
        "result": {
            "metric": f"2D consensus ADMM outer iters/sec (k=8{suffix})",
            "value": value,
            "vs_baseline": value / (20.0 / 300.0),
            "knobs": knobs or {},
        },
    }


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_pick():
    spec = importlib.util.spec_from_file_location(
        "pick_tuned_for_test",
        os.path.join(REPO, "scripts", "pick_tuned.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_onchip_record_picks_newest_real_chip_row(tmp_path):
    bench = _load_bench()
    old = tmp_path / "onchip_r4.jsonl"
    new = tmp_path / "onchip_r5.jsonl"
    _write_jsonl(old, [
        _rec("baseline", 1.15),
        _rec("tuned", 1.81, knobs={"fft_impl": "matmul"}),
    ])
    _write_jsonl(new, [
        {"note": "phase arms start"},
        _rec("cpu_thing", 9.9, chip=False),
        _rec("degraded_thing", 9.9, degraded=True),
        _rec("fresh", 2.5, knobs={"fused_z": True}),
        _rec("zero", 0.0),
    ])
    os.utime(old, (time.time() - 7200, time.time() - 7200))
    bench.REPO = str(tmp_path)
    rec, fastest = bench.last_onchip_record()
    assert rec["run"] == "fresh"
    assert rec["value"] == 2.5
    assert rec["source"] == "onchip_r5.jsonl"
    assert rec["knobs"] == {"fused_z": True}
    assert rec["source_age_hours"] < 1.0
    # fastest is restricted to the SAME newest file: the r4 tuned row
    # (1.81, older code) must not leak in even though it is a valid
    # chip row
    assert fastest["run"] == "fresh" and fastest["source"] == "onchip_r5.jsonl"


def test_last_onchip_fastest_may_differ_from_last(tmp_path):
    bench = _load_bench()
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("speedster", 3.1, knobs={"fused_z_precision": "default"}),
        _rec("tuned_rerun", 2.5, knobs={"fused_z": True}),
    ])
    bench.REPO = str(tmp_path)
    last, fastest = bench.last_onchip_record()
    assert last["run"] == "tuned_rerun"
    assert fastest["run"] == "speedster" and fastest["value"] == 3.1


def test_emit_best_onchip_only_when_strictly_faster(tmp_path, capsys):
    """emit() must compare VALUES, not object identity: an earlier arm
    that ties the newest record is not a distinct faster record and
    must not be re-emitted as best_onchip (ADVICE r5)."""
    bench = _load_bench()
    bench.REPO = str(tmp_path)
    r = {"iters_per_sec": 0.01, "n": 8, "size": 24, "k": 8, "blocks": 2,
         "platform": "cpu"}
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("early_tie", 2.5),
        _rec("newest", 2.5),
    ])
    bench.emit(r, degraded=True)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["last_onchip"]["run"] == "newest"
    assert "best_onchip" not in out
    # a strictly faster earlier arm still surfaces
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("speedster", 3.0),
        _rec("newest", 2.5),
    ])
    bench.emit(r, degraded=True)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["best_onchip"]["run"] == "speedster"


def test_last_onchip_record_none_when_no_chip_rows(tmp_path):
    bench = _load_bench()
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("only_degraded", 1.0, degraded=True),
        {"note": "nothing real"},
    ])
    bench.REPO = str(tmp_path)
    assert bench.last_onchip_record() == (None, None)


def test_pick_tuned_uses_only_newest_round(tmp_path, capsys):
    pt = _load_pick()
    old = tmp_path / "onchip_r4.jsonl"
    new = tmp_path / "onchip_r5.jsonl"
    # old round has a FASTER arm (stale tunnel, stale code) that must
    # NOT win over the new round's slower-but-current measurements
    _write_jsonl(old, [
        _rec("baseline", 1.0),
        _rec("stale_fast", 5.0, knobs={"fft_impl": "matmul_bf16"}),
    ])
    _write_jsonl(new, [
        _rec("baseline", 1.0),
        _rec("current_win", 1.5, knobs={"fft_impl": "matmul",
                                        "storage_dtype": "bfloat16"}),
    ])
    os.utime(old, (time.time() - 7200, time.time() - 7200))
    pt.REPO = str(tmp_path)
    pt.TUNED = str(tmp_path / "bench_tuned.json")
    assert pt.main() == 0
    tuned = json.load(open(pt.TUNED))
    assert tuned == {"fft_impl": "matmul", "storage_dtype": "bfloat16"}


def test_pick_tuned_defaults_when_baseline_wins(tmp_path):
    pt = _load_pick()
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("baseline", 2.0),
        _rec("loser", 1.5, knobs={"fft_impl": "matmul"}),
    ])
    pt.REPO = str(tmp_path)
    pt.TUNED = str(tmp_path / "bench_tuned.json")
    # pre-existing stale tuned file must be removed
    with open(pt.TUNED, "w") as f:
        json.dump({"fft_impl": "matmul"}, f)
    assert pt.main() == 0
    assert not os.path.exists(pt.TUNED)


def test_pick_tuned_accuracy_gate_rejects_measured_inaccurate_knob(
    tmp_path, capsys
):
    """A faster arm whose knob has an on-chip accuracy record above
    ACC_BOUND must lose to a slower arm in the documented accuracy
    class (r5: matmul_bf16 at 2.6%% objective deviation must not become
    the tuned DEFAULT on speed alone)."""
    pt = _load_pick()
    rows = [
        _rec("baseline", 1.0),
        _rec("fast_inaccurate", 2.0, knobs={"fft_impl": "matmul_bf16"}),
        _rec("accurate", 1.5, knobs={"fft_impl": "matmul"}),
        {"config": "matmul_bf16prec", "obj_final": 1.0, "platform": "tpu",
         "max_rel_obj_dev_vs_ref": 0.026},
        {"config": "matmul", "obj_final": 1.0, "platform": "tpu",
         "max_rel_obj_dev_vs_ref": 8.6e-07},
    ]
    _write_jsonl(tmp_path / "onchip_r5.jsonl", rows)
    pt.REPO = str(tmp_path)
    pt.TUNED = str(tmp_path / "bench_tuned.json")
    assert pt.main() == 0
    assert json.load(open(pt.TUNED)) == {"fft_impl": "matmul"}
    assert "accuracy gate" in capsys.readouterr().out


def test_pick_tuned_accuracy_gate_passes_unmeasured_knob(tmp_path):
    """Knobs without an accuracy record keep r4 behavior (the gate is
    evidence-driven): a short tunnel window that only measured arms
    must still yield a tuned config."""
    pt = _load_pick()
    _write_jsonl(tmp_path / "onchip_r5.jsonl", [
        _rec("baseline", 1.0),
        _rec("win", 1.5, knobs={"fft_impl": "matmul"}),
    ])
    pt.REPO = str(tmp_path)
    pt.TUNED = str(tmp_path / "bench_tuned.json")
    assert pt.main() == 0
    assert json.load(open(pt.TUNED)) == {"fft_impl": "matmul"}
