"""Autotune subsystem (tune/): arm space, store, resolver, guard.

Covers the ISSUE-6 acceptance set:
- deterministic sweep with injected timers
- store persistence round-trip + key (chip / fingerprint) mismatch
  refusal — a DEGRADED/cross-chip record must never configure a run
- the numerics guard demoting a deliberately-poisoned arm and
  applying the next-best
- the store pre-seeded from onchip_r5.jsonl resolving a --tune auto
  learner to the best_onchip arm (bf16 + matmul-DFT + fused_z +
  schur) with zero hand-set knob flags
- the serving engine picking tuned knobs at startup and recording the
  resolved knob dict in its warmup events
- serving bit-identity preserved when tuning is off
- the knob drift guard: every LearnConfig/SolveConfig field is
  classified (tuned or explicitly non-tuned), so a new perf knob
  cannot silently escape the tuner's candidate space
- scripts/autotune.py --dry-run validating the arm space without a
  chip

Hermetic: tune='off' is the config default, every store lives in
tmp_path, and chips are pinned explicitly — nothing here touches the
repo-root tuned_knobs.json.
"""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ccsc_code_iccv2017_tpu import config  # noqa: E402
from ccsc_code_iccv2017_tpu.config import (  # noqa: E402
    GEOM_2D, LearnConfig, ProblemGeom, ServeConfig, SolveConfig,
)
from ccsc_code_iccv2017_tpu.tune import (  # noqa: E402
    autotune, space, store as ts,
)


# ---------------------------------------------------------------------
# arm space + drift guard
# ---------------------------------------------------------------------

def test_tune_off_is_the_default():
    # tier-1 hermeticity: pytest must never see resolution unless a
    # test opts in explicitly
    assert LearnConfig().tune == "off"
    assert SolveConfig().tune == "off"
    with pytest.raises(ValueError):
        LearnConfig(tune="fastest")
    with pytest.raises(ValueError):
        SolveConfig(tune="fastest")


def test_every_config_field_is_classified():
    """The drift guard: a knob added to LearnConfig/SolveConfig
    without a tuner-space classification fails here — new perf knobs
    cannot silently escape tuning."""
    for kind, cls in (
        ("learn", config.LearnConfig), ("solve", config.SolveConfig)
    ):
        unclassified, missing = space.classify_drift(kind, cls)
        assert not unclassified, (
            f"{cls.__name__} fields not classified in "
            f"tune.space: {sorted(unclassified)} — add each to "
            f"{kind.upper()}_KNOBS (tunable) or NON_TUNED_"
            f"{kind.upper()} (with the reason)"
        )
        assert not missing, (
            f"tune.space declares {kind} field knobs that "
            f"{cls.__name__} does not have: {sorted(missing)}"
        )


def test_default_arms_apply_cleanly():
    for kind, cfg, workload in (
        ("learn", LearnConfig(), "consensus2d"),
        ("solve", SolveConfig(), "solve2d"),
    ):
        arms = space.default_arms(kind, workload)
        assert {} in arms and len(arms) > 5
        for arm in arms:
            armed, env, dropped = space.apply_arm(
                cfg, arm, kind, workload
            )
            assert not dropped
            for name, v in arm.items():
                if space.knobs(kind)[name].field:
                    assert getattr(armed, name) == v


def test_apply_arm_drops_inapplicable_knobs():
    arm = {"fused_z": True, "storage_dtype": "bfloat16"}
    armed, _, dropped = space.apply_arm(
        LearnConfig(), arm, "learn", "masked2d"
    )
    assert armed.storage_dtype == "bfloat16"
    assert armed.fused_z is False  # consensus2d-only knob
    assert dropped and dropped[0][0] == "fused_z"
    # streaming drops donation too
    armed, _, dropped = space.apply_arm(
        LearnConfig(), {"donate_state": True}, "learn", "streaming2d"
    )
    assert armed.donate_state is False
    assert dropped


def test_dry_run_validates_without_a_chip():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "autotune.py"),
         "--dry-run"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-500:]
    assert "candidate arms" in out.stdout
    assert "code fingerprint" in out.stdout


# ---------------------------------------------------------------------
# store: round-trip, key mismatch refusal, seeding
# ---------------------------------------------------------------------

def test_store_round_trip_and_ranking(tmp_path):
    path = str(tmp_path / "store.json")
    st = ts.TunedStore(path)
    assert st.empty
    st.add("v5e", "learn", "key1", {"fft_impl": "matmul"}, 2.0,
           "outer_iters/sec", source="a")
    st.add("v5e", "learn", "key1", {"fused_z": True}, 3.0,
           "outer_iters/sec", source="b")
    st.add("v5e", "learn", "key1", {}, 1.0, "outer_iters/sec")
    st.save()
    st2 = ts.TunedStore(path)
    cands = st2.candidates("v5e", "learn", "key1")
    assert [c["value"] for c in cands] == [3.0, 2.0, 1.0]
    # demotion round-trips
    st2.demote("v5e", "learn", "key1", {"fused_z": True}, reason="x")
    st2.save()
    st3 = ts.TunedStore(path)
    assert [c["value"] for c in st3.candidates("v5e", "learn", "key1")] \
        == [2.0, 1.0]
    # re-adding a demoted arm clears the demotion (fresh measurement)
    st3.add("v5e", "learn", "key1", {"fused_z": True}, 4.0,
            "outer_iters/sec")
    assert st3.candidates("v5e", "learn", "key1")[0]["value"] == 4.0


def test_store_refuses_cross_chip_and_stale_fingerprint(tmp_path):
    path = str(tmp_path / "store.json")
    st = ts.TunedStore(path)
    st.add("v5e", "learn", "key1", {"fft_impl": "matmul"}, 2.0,
           "outer_iters/sec")
    # cross-chip: a v5e winner must never configure a cpu run
    assert st.candidates("cpu", "learn", "key1") == []
    assert st.chips_with_entries("learn", "key1") == ["v5e"]
    # stale code fingerprint: entries from an older knob vocabulary
    # stop matching
    st._data["v5e|learn|key1"][0]["fp"] = "stale000000"
    assert st.candidates("v5e", "learn", "key1") == []
    # ...and a stale entry no longer counts as "tuned entries exist
    # for chip v5e": the cross-chip refusal diagnosis applies the
    # same eligibility filter as candidates(), so a fully stale (or
    # demoted) store falls through to "no tuned entry" / the legacy
    # bench shim instead of a self-contradictory refusal
    assert st.chips_with_entries("learn", "key1") == []
    st._data["v5e|learn|key1"][0]["fp"] = ts.space.code_fingerprint()
    st.demote("v5e", "learn", "key1", {"fft_impl": "matmul"}, "test")
    assert st.chips_with_entries("learn", "key1") == []


def test_store_corrupt_file_reads_as_empty(tmp_path):
    path = tmp_path / "store.json"
    path.write_text("{not json")
    st = ts.TunedStore(str(path))
    assert st.empty
    st.add("cpu", "solve", "k", {}, 1.0, "solves/sec")
    st.save()
    assert not ts.TunedStore(str(path)).empty


def test_seed_skips_degraded_and_failed_records(tmp_path):
    rows = [
        {"run": "degraded", "result": {
            "metric": "2D consensus ADMM outer iters/sec (k=8 11x11 "
            "filters, n=16x32^2, 2 blocks, DEGRADED: TPU unreachable, "
            "ran on cpu)",
            "value": 9.9, "unit": "outer_iters/sec", "chip": "cpu",
            "knobs": {"fft_impl": "matmul"}}},
        {"run": "failed", "result": {
            "metric": "2D consensus ADMM outer iters/sec (FAILED: "
            "TPU attempt did not complete)", "value": 0.0}},
        {"run": "serving", "result": {
            "metric": "serving engine requests/sec (x, 1 chip)",
            "value": 5.0, "unit": "requests/sec", "chip": "v5e"}},
        {"run": "chipless", "result": {
            "metric": "2D consensus ADMM outer iters/sec (k=8 11x11 "
            "filters, n=16x32^2, 2 blocks, 1 chip)",
            "value": 2.0, "unit": "outer_iters/sec"}},
        {"run": "good", "result": {
            "metric": "2D consensus ADMM outer iters/sec (k=8 11x11 "
            "filters, n=16x32^2, 2 blocks, 1 chip)",
            "value": 2.0, "unit": "outer_iters/sec", "chip": "v5e",
            "knobs": {"fft_impl": "matmul", "fft_pad": "none"}}},
    ]
    p = tmp_path / "onchip_r9.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    st = ts.TunedStore(str(tmp_path / "store.json"))
    assert ts.seed_from_onchip(st, str(p)) == 1
    key = ts.learn_shape_key(
        "consensus2d", k=8, support=(11, 11), n=16, size=(32, 32),
        blocks=2,
    )
    cands = st.candidates("v5e", "learn", key)
    assert len(cands) == 1
    # default-valued knobs are stripped; only the real move remains
    assert cands[0]["arm"] == {"fft_impl": "matmul"}
    # the DEGRADED row seeded nothing anywhere
    assert st.chips_with_entries("learn", key) == ["v5e"]


# ---------------------------------------------------------------------
# resolution: the acceptance path from the real on-chip record
# ---------------------------------------------------------------------

def test_seeded_store_resolves_learner_to_best_onchip(
    tmp_path, monkeypatch
):
    """ISSUE-6 acceptance: with the store pre-seeded from
    onchip_r5.jsonl, a learner config with tune='auto' (zero hand-set
    knob flags) resolves to the best_onchip arm — bf16 storage,
    matmul-DFT, fused_z, Schur inverse (46.2x baseline,
    BENCH_r05.json)."""
    # setenv (not delenv) so monkeypatch RECORDS the variable and
    # restores its absence afterwards — resolve_learn writes
    # CCSC_HERM_INV, and a leak would flip the Gram-inverse method of
    # every later test in this process
    monkeypatch.setenv("CCSC_HERM_INV", "")
    st = ts.TunedStore(str(tmp_path / "store.json"))
    n = ts.seed_from_onchip(
        st, os.path.join(REPO, "onchip_r5.jsonl")
    )
    assert n >= 10  # the round measured a full arm ladder
    cfg = LearnConfig(tune="auto", num_blocks=8)
    # chip pinned to the record's chip; guard=False because the
    # fused-kernel arm cannot run on the CI host — the guard's own
    # demotion behavior is covered by test_guard_demotes_poisoned_arm
    resolved, picked = autotune.resolve_learn(
        cfg, GEOM_2D(100, 11), (128, 100, 100),
        workload="consensus2d", chip="v5e", store=st, guard=False,
    )
    assert picked is not None
    assert picked["source"].endswith("fused_default_schur")
    assert resolved.storage_dtype == "bfloat16"
    assert resolved.d_storage_dtype == "bfloat16"
    assert resolved.fft_impl == "matmul_bf16"
    assert resolved.fused_z is True
    assert resolved.fused_z_precision == "default"
    assert resolved.tune == "off"  # consumed — no re-resolution
    # the env-level knob of the arm (learners read CCSC_HERM_INV at
    # trace time) was applied at startup
    assert os.environ.get("CCSC_HERM_INV") == "schur"
    # and a CPU run must refuse the same v5e entries outright
    events = []
    cfg_cpu, picked_cpu = autotune.resolve_learn(
        LearnConfig(tune="auto", num_blocks=8), GEOM_2D(100, 11),
        (128, 100, 100), workload="consensus2d", chip="cpu", store=st,
        guard=False, emit=lambda t, **f: events.append((t, f)),
    )
    assert picked_cpu is None
    assert cfg_cpu.fft_impl == "xla"
    assert any(
        t == "tune_pick" and "cross-chip" in (f.get("reason") or "")
        for t, f in events
    )


def test_resolve_no_entries_keeps_defaults(tmp_path):
    st = ts.TunedStore(str(tmp_path / "store.json"))
    events = []
    cfg, picked = autotune.resolve_learn(
        LearnConfig(tune="auto"), GEOM_2D(8, 5), (4, 16, 16),
        chip="cpu", store=st, guard=False,
        emit=lambda t, **f: events.append((t, f)),
    )
    assert picked is None and cfg.fft_impl == "xla"
    assert events and events[0][0] == "tune_pick"


# ---------------------------------------------------------------------
# deterministic sweep with injected timers
# ---------------------------------------------------------------------

def test_sweep_with_injected_timer_ranks_and_persists(tmp_path):
    st = ts.TunedStore(str(tmp_path / "store.json"))
    speeds = {
        "baseline": 1.0,
        "fft_impl=matmul": 3.0,
        "storage_dtype=bfloat16": 2.0,
        "fft_pad=pow2": 0.5,  # a loser: must be demoted post-sweep
    }
    arms = [{}, {"fft_impl": "matmul"}, {"storage_dtype": "bfloat16"},
            {"fft_pad": "pow2"}]
    events = []
    autotune.sweep_learn(
        LearnConfig(num_blocks=2), GEOM_2D(8, 5), (8, 24, 24),
        chip="cpu", store=st, arms=arms,
        timer=lambda armed, arm: speeds[space.arm_label(arm)],
        emit=lambda t, **f: events.append((t, f)),
    )
    key = ts.learn_shape_key(
        "consensus2d", k=8, support=(5, 5), n=8, size=(24, 24),
        blocks=2,
    )
    st2 = ts.TunedStore(str(tmp_path / "store.json"))  # round-trip
    cands = st2.candidates("cpu", "learn", key)
    assert [c["value"] for c in cands] == [3.0, 2.0, 1.0]
    assert cands[0]["arm"] == {"fft_impl": "matmul"}
    # the slower-than-baseline arm was demoted, not kept as a
    # fallback candidate
    assert all(c["arm"] != {"fft_pad": "pow2"} for c in cands)
    assert sum(1 for t, _ in events if t == "tune_arm") == 4
    # and resolution picks the injected winner
    cfg, picked = autotune.resolve_learn(
        LearnConfig(tune="auto", num_blocks=2), GEOM_2D(8, 5),
        (8, 24, 24), chip="cpu", store=st2, guard=False,
    )
    assert cfg.fft_impl == "matmul"


def test_sweep_timer_failure_records_no_entry(tmp_path):
    st = ts.TunedStore(str(tmp_path / "store.json"))

    def timer(armed, arm):
        if arm:
            raise RuntimeError("backend cannot run this knob")
        return 1.0

    autotune.sweep_learn(
        LearnConfig(num_blocks=2), GEOM_2D(8, 5), (8, 24, 24),
        chip="cpu", store=st, arms=[{}, {"fft_impl": "matmul"}],
        timer=timer, emit=lambda t, **f: None,
    )
    key = ts.learn_shape_key(
        "consensus2d", k=8, support=(5, 5), n=8, size=(24, 24),
        blocks=2,
    )
    cands = st.candidates("cpu", "learn", key)
    assert [c["arm"] for c in cands] == [{}]


# ---------------------------------------------------------------------
# numerics guard: demote a poisoned arm, apply the next best
# ---------------------------------------------------------------------

def test_guard_demotes_poisoned_arm_and_applies_next_best(
    tmp_path, monkeypatch
):
    """The REAL guard on a REAL numerics difference: bf16 iterate
    storage rounds the stored trajectory (~1e-4 relative on the tiny
    guard problem), matmul-DFT matches to float rounding (~1e-7). A
    guard tolerance between the two demotes the 'poisoned' bf16 arm
    and applies the matmul arm — persisting the demotion so the next
    startup skips straight to the survivor."""
    monkeypatch.setenv("CCSC_TUNE_GUARD_TOL", "1e-5")
    path = str(tmp_path / "store.json")
    st = ts.TunedStore(path)
    key_args = dict(k=8, support=(5, 5), n=4, size=(16, 16), blocks=2)
    key = ts.learn_shape_key("consensus2d", **key_args)
    st.add("cpu", "learn", key, {"storage_dtype": "bfloat16"}, 9.0,
           "outer_iters/sec", source="poisoned")
    st.add("cpu", "learn", key, {"fft_impl": "matmul"}, 5.0,
           "outer_iters/sec", source="survivor")
    st.save()
    events = []
    cfg, picked = autotune.resolve_learn(
        LearnConfig(tune="auto", num_blocks=2), GEOM_2D(8, 5),
        (4, 16, 16), chip="cpu", store=st,
        emit=lambda t, **f: events.append((t, f)),
    )
    assert picked is not None and picked["source"] == "survivor"
    assert cfg.fft_impl == "matmul"
    assert cfg.storage_dtype == "float32"
    guards = [f for t, f in events if t == "tune_guard"]
    assert [g["ok"] for g in guards] == [False, True]
    # the demotion persisted: a fresh load skips the poisoned arm
    st2 = ts.TunedStore(path)
    cands = st2.candidates("cpu", "learn", key)
    assert [c["source"] for c in cands] == ["survivor"]
    # and the survivor's guard verdict is cached — a second startup
    # resolves without re-running any guard
    events2 = []
    cfg2, picked2 = autotune.resolve_learn(
        LearnConfig(tune="auto", num_blocks=2), GEOM_2D(8, 5),
        (4, 16, 16), chip="cpu", store=st2,
        emit=lambda t, **f: events2.append((t, f)),
        guard=lambda *a: (_ for _ in ()).throw(
            AssertionError("guard must not re-run")
        ),
    )
    assert picked2 is not None and cfg2.fft_impl == "matmul"


def test_injected_guard_flow(tmp_path):
    """Resolver mechanics with an injected guard: reject the top arm,
    accept the next."""
    st = ts.TunedStore(str(tmp_path / "store.json"))
    st.add("cpu", "solve", "key", {"fft_impl": "matmul_bf16"}, 9.0,
           "solves/sec")
    st.add("cpu", "solve", "key", {"fft_impl": "matmul"}, 5.0,
           "solves/sec")
    calls = []

    def guard(kind, arm, tol):
        calls.append(arm)
        return arm != {"fft_impl": "matmul_bf16"}, 1.0

    cfg, picked, env = autotune._resolve(
        "solve", SolveConfig(), "key", "solve2d", "cpu", st,
        lambda t, **f: None, guard,
    )
    assert cfg.fft_impl == "matmul"
    assert len(calls) == 2


def test_all_arms_demoted_falls_back_to_defaults(tmp_path):
    st = ts.TunedStore(str(tmp_path / "store.json"))
    st.add("cpu", "solve", "key", {"fft_impl": "matmul"}, 5.0,
           "solves/sec")
    events = []
    cfg, picked, _ = autotune._resolve(
        "solve", SolveConfig(), "key", "solve2d", "cpu", st,
        lambda t, **f: events.append((t, f)),
        lambda kind, arm, tol: (False, float("inf")),
    )
    assert picked is None and cfg.fft_impl == "xla"
    assert events[-1][0] == "tune_pick" and \
        "demoted" in events[-1][1]["reason"]


# ---------------------------------------------------------------------
# engine startup + serving contracts
# ---------------------------------------------------------------------

def _unit_bank(k=4, sup=5, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, sup, sup)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return d


def test_engine_startup_picks_tuned_knobs(tmp_path):
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine
    from ccsc_code_iccv2017_tpu.utils import obs

    d = _unit_bank()
    geom = ProblemGeom((5, 5), 4)
    spath = str(tmp_path / "store.json")
    st = ts.TunedStore(spath)
    st.add(
        "cpu", "solve",
        ts.solve_shape_key(
            "solve2d", k=4, support=(5, 5), spatial=(24, 24)
        ),
        {"fft_impl": "matmul"}, 9.0, "solves/sec", source="seeded",
    )
    st.save()
    mdir = str(tmp_path / "metrics")
    cfg = SolveConfig(
        max_it=4, tol=0.0, verbose="none", lambda_prior=0.3
    )
    scfg = ServeConfig(
        buckets=((2, (24, 24)),), metrics_dir=mdir, verbose="none",
        tune="auto", tune_store=spath,
    )
    with CodecEngine(
        jnp.asarray(d), ReconstructionProblem(geom), cfg, scfg
    ) as eng:
        assert eng.cfg.fft_impl == "matmul"  # tuned arm applied
        r = np.random.default_rng(1)
        x = r.random((16, 16)).astype(np.float32)
        m = (r.random((16, 16)) < 0.6).astype(np.float32)
        res = eng.reconstruct(x * m, mask=m)
        assert int(res.trace.num_iters) == 4
    events = obs.read_events(mdir)
    picks = [e for e in events if e.get("type") == "tune_pick"]
    assert picks and picks[0]["arm"] == {"fft_impl": "matmul"}
    # satellite: warmup events carry the RESOLVED knob dict, not just
    # the bucket shape — the stream says which arm served
    warmups = [e for e in events if e.get("type") == "serve_warmup"]
    assert warmups
    for w in warmups:
        assert w["knobs"]["fft_impl"] == "matmul"
        assert w["knobs"]["tuned"] is True
        assert w["knobs"]["tune"] == "auto"


def test_engine_tune_off_serving_bit_identity(tmp_path):
    """With tuning off (the default), an exact-bucket served result
    stays BIT-identical to a direct reconstruct() call — the
    autotune layer must be invisible when not engaged."""
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine

    d = _unit_bank()
    geom = ProblemGeom((5, 5), 4)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(
        max_it=5, tol=0.0, verbose="none", lambda_prior=0.3,
        track_objective=True,
    )
    scfg = ServeConfig(buckets=((2, (16, 16)),), verbose="none")
    assert scfg.tune == "off"
    r = np.random.default_rng(2)
    x = r.random((16, 16)).astype(np.float32)
    m = (r.random((16, 16)) < 0.6).astype(np.float32)
    with CodecEngine(jnp.asarray(d), prob, cfg, scfg) as eng:
        assert eng._knob_dict["tuned"] is False
        served = eng.reconstruct(x * m, mask=m)
    direct = reconstruct(
        jnp.asarray((x * m)[None]), jnp.asarray(d), prob, cfg,
        mask=jnp.asarray(m[None]),
    )
    np.testing.assert_array_equal(
        served.recon, np.asarray(direct.recon[0])
    )


def test_reconstruct_storage_dtype_stays_in_tolerance():
    """SolveConfig.storage_dtype='bfloat16' (the serving analog of the
    learners' bf16 code storage) perturbs the solve only in the
    documented small class; f32 keeps the program byte-identical by
    construction (identity casts)."""
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )

    d = _unit_bank()
    geom = ProblemGeom((5, 5), 4)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(
        max_it=6, tol=0.0, verbose="none", lambda_prior=0.3
    )
    r = np.random.default_rng(3)
    x = r.random((2, 16, 16)).astype(np.float32)
    m = (r.random((2, 16, 16)) < 0.6).astype(np.float32)
    ref = reconstruct(
        jnp.asarray(x * m), jnp.asarray(d), prob, cfg,
        mask=jnp.asarray(m),
    )
    got = reconstruct(
        jnp.asarray(x * m), jnp.asarray(d), prob,
        dataclasses.replace(cfg, storage_dtype="bfloat16"),
        mask=jnp.asarray(m),
    )
    rec_ref = np.asarray(ref.recon)
    rec_got = np.asarray(got.recon)
    rel = np.abs(rec_got - rec_ref).max() / max(
        np.abs(rec_ref).max(), 1e-9
    )
    assert 0 < rel < 0.02  # perturbed, but in the bf16 storage class


def test_reconstruct_inline_tune_auto(tmp_path, monkeypatch):
    """SolveConfig.tune='auto' resolves inside reconstruct() for the
    coding-app path (no engine)."""
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )

    spath = str(tmp_path / "store.json")
    monkeypatch.setenv("CCSC_TUNE_STORE", spath)
    st = ts.TunedStore(spath)
    st.add(
        "cpu", "solve",
        ts.solve_shape_key(
            "solve2d", k=4, support=(5, 5), spatial=(16, 16)
        ),
        {"fft_impl": "matmul"}, 9.0, "solves/sec",
    )
    st.save()
    d = _unit_bank()
    geom = ProblemGeom((5, 5), 4)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(
        max_it=4, tol=0.0, verbose="none", lambda_prior=0.3,
        tune="auto",
    )
    r = np.random.default_rng(4)
    x = r.random((1, 16, 16)).astype(np.float32)
    m = (r.random((1, 16, 16)) < 0.6).astype(np.float32)
    res = reconstruct(
        jnp.asarray(x * m), jnp.asarray(d), prob, cfg,
        mask=jnp.asarray(m),
    )
    assert int(res.trace.num_iters) == 4
    ref = reconstruct(
        jnp.asarray(x * m), jnp.asarray(d), prob,
        dataclasses.replace(cfg, tune="off", fft_impl="matmul"),
        mask=jnp.asarray(m),
    )
    np.testing.assert_array_equal(
        np.asarray(res.recon), np.asarray(ref.recon)
    )


# ---------------------------------------------------------------------
# bench tooling unification
# ---------------------------------------------------------------------

def test_bench_lookup_prefers_store_then_shim_then_refuses(tmp_path):
    repo = str(tmp_path)
    spath = os.path.join(repo, "tuned_knobs.json")
    shape = dict(k=100, support=(11, 11), n=128, size=(100, 100),
                 blocks=8)
    # 1) no store, no legacy file -> defaults
    knobs, src = ts.bench_lookup("v5e", repo=repo, **shape)
    assert knobs == {} and src == "none"
    # 2) legacy bench_tuned.json only -> migration shim
    with open(os.path.join(repo, "bench_tuned.json"), "w") as f:
        json.dump({"fft_impl": "matmul", "herm_inv": "schur"}, f)
    knobs, src = ts.bench_lookup("v5e", repo=repo, **shape)
    assert knobs["fft_impl"] == "matmul"
    assert src == "legacy:bench_tuned.json"
    # 3) store entry wins over the shim
    st = ts.TunedStore(spath)
    key = ts.learn_shape_key("consensus2d", **shape)
    st.add("v5e", "learn", key, {"fused_z": True}, 3.0,
           "outer_iters/sec", source="r5")
    st.save()
    knobs, src = ts.bench_lookup("v5e", repo=repo, **shape)
    assert knobs == {"fused_z": True} and src.startswith("store:")
    # 4) wrong chip REFUSES (no silent legacy fallback: the shim
    # carries the same cross-chip hazard)
    knobs, src = ts.bench_lookup("cpu", repo=repo, **shape)
    assert knobs == {} and src.startswith("refused")


def test_pick_tuned_seeds_the_store(tmp_path, capsys):
    import importlib.util
    import time as _time

    spec = importlib.util.spec_from_file_location(
        "pick_tuned_for_autotune_test",
        os.path.join(REPO, "scripts", "pick_tuned.py"),
    )
    pt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pt)
    rows = [
        {"run": "baseline", "result": {
            "metric": "2D consensus ADMM outer iters/sec (k=100 11x11 "
            "filters, n=128x100^2, 8 blocks, 1 chip)",
            "value": 1.0, "unit": "outer_iters/sec", "chip": "v5e",
            "knobs": {"fft_impl": "xla"}}},
        {"run": "win", "result": {
            "metric": "2D consensus ADMM outer iters/sec (k=100 11x11 "
            "filters, n=128x100^2, 8 blocks, 1 chip)",
            "value": 1.5, "unit": "outer_iters/sec", "chip": "v5e",
            "knobs": {"fft_impl": "matmul"}}},
    ]
    (tmp_path / "onchip_r5.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\n"
    )
    pt.REPO = str(tmp_path)
    pt.TUNED = str(tmp_path / "bench_tuned.json")
    assert pt.main() == 0
    # flat-file pick unchanged (read-compat shim)
    assert json.load(open(pt.TUNED))["fft_impl"] == "matmul"
    # AND the store now holds the ranked arms for the chip key
    st = ts.TunedStore(str(tmp_path / "tuned_knobs.json"))
    key = ts.learn_shape_key(
        "consensus2d", k=100, support=(11, 11), n=128,
        size=(100, 100), blocks=8,
    )
    cands = st.candidates("v5e", "learn", key)
    assert [c["value"] for c in cands] == [1.5, 1.0]
