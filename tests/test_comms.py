"""The collective-budget gate (analysis.comms, ISSUE 20).

Contracts under test:

- STATIC COUNTING: collective_counts counts op DEFINITIONS in HLO
  text — word-boundary exact (identifier tails like `%all-gather.5`
  and longer embedding mnemonics like `ragged-all-to-all(` must not
  inflate a shorter class), async `-start` halves count once, and
  reduce-scatter books under the reduce class;
- DECLARED BUDGETS: batch-only serving meshes declare ZERO, freq
  meshes declare CCSC_COMM_BUDGET_FREQ (default 1, env-overridable);
- ENFORCEMENT: check() raises CommBudgetError on an overrun with
  enforcement armed (the default) and stays silent under
  CCSC_COMM_BUDGET_ENFORCE=0 — audit-and-record, never serve-and-hide;
- program_counts returns None for anything without a stable text dump
  (lazily-jitted callables have nothing to audit);
- THE ENGINE GATE: a mesh engine whose bucket program "contains" an
  injected collective (comms.program_counts monkeypatched) refuses to
  finish warmup with CommBudgetError; with enforcement off it builds
  and records the failing verdict (comm_audit event, ok=False).

The live end-to-end property — the real batch-mesh program lowering
to zero collectives on 8 forced host devices — is asserted by
tests/test_serve_mesh.py (the CCSC_CI_DEVICES leg) and
scripts/comm_audit.py (the ci.sh exit-29 leg); these tests pin the
accounting and the refusal machinery around it.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.analysis import comms
from ccsc_code_iccv2017_tpu.config import (
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine
from ccsc_code_iccv2017_tpu.utils import obs

needs2 = pytest.mark.skipif(
    jax.device_count() < 2,
    reason="needs >= 2 (forced host) devices for a (2,) serving mesh",
)


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    for v in (
        "CCSC_COMM_BUDGET_ENFORCE",
        "CCSC_COMM_BUDGET_FREQ",
        "CCSC_SERVE_MESH",
        "CCSC_PERF_LEDGER",
    ):
        monkeypatch.delenv(v, raising=False)
    yield


# ------------------------------------------------------ text counting


HLO_FIXTURE = """\
ENTRY %main (p0: f32[8,4]) -> f32[8,8] {
  %p0 = f32[8,4]{1,0} parameter(0)
  %ag = f32[8,8]{1,0} all-gather(f32[8,4]{1,0} %p0), dimensions={1}
  %ags = f32[8,8]{1,0} all-gather-start(f32[8,4]{1,0} %p0)
  %agd = f32[8,8]{1,0} all-gather-done(f32[8,8]{1,0} %ags)
  %ar = f32[8,4]{1,0} all-reduce(f32[8,4]{1,0} %p0), to_apply=%add
  %rs = f32[4,4]{1,0} reduce-scatter(f32[8,4]{1,0} %p0), to_apply=%add
  %rata = f32[8,4]{1,0} ragged-all-to-all(f32[8,4]{1,0} %p0)
  %cp = f32[8,4]{1,0} collective-permute(f32[8,4]{1,0} %p0)
  %use = f32[8,8]{1,0} copy(f32[8,8]{1,0} %all-gather.5)
}
"""


def test_collective_counts_fixture_word_boundaries():
    c = comms.collective_counts(HLO_FIXTURE)
    # all-gather( + all-gather-start( ; NOT all-gather-done( (done is
    # the same logical collective) and NOT the %all-gather.5 use
    assert c["all_gather"] == 2
    # all-reduce( + reduce-scatter(
    assert c["all_reduce"] == 2
    # ragged-all-to-all( counts ONCE — not also as all-to-all(
    assert c["all_to_all"] == 1
    assert c["collective_permute"] == 1
    assert c["total"] == 6


def test_collective_counts_clean_text_is_zero():
    c = comms.collective_counts(
        "ENTRY %main { %p = f32[4]{0} parameter(0)\n"
        "  %r = f32[4]{0} add(%p, %p) }"
    )
    assert c["total"] == 0
    assert all(v == 0 for k, v in c.items())
    assert comms.format_counts(c) == "none"


def test_declared_budget_mapping(monkeypatch):
    assert comms.declared_budget(None) == 0
    assert comms.declared_budget(()) == 0
    assert comms.declared_budget((4,)) == 0
    assert comms.declared_budget((4, 1)) == 0  # trivial freq axis
    assert comms.declared_budget((4, 2)) == 1  # default freq budget
    monkeypatch.setenv("CCSC_COMM_BUDGET_FREQ", "3")
    assert comms.declared_budget((4, 2)) == 3
    assert comms.declared_budget((8,)) == 0  # batch stays zero


def test_check_raises_over_budget_and_respects_enforce(monkeypatch):
    over = comms.collective_counts(HLO_FIXTURE)
    with pytest.raises(comms.CommBudgetError, match="declared budget"):
        comms.check(over, (8,), bucket="b8x12x12")
    # a freq mesh with counts inside its budget passes
    one = {"all_gather": 1, "all_reduce": 0, "all_to_all": 0,
           "collective_permute": 0, "total": 1}
    comms.check(one, (4, 2), bucket="ok")
    # enforcement off: the overrun is recorded by callers, not raised
    monkeypatch.setenv("CCSC_COMM_BUDGET_ENFORCE", "0")
    comms.check(over, (8,), bucket="b8x12x12")
    assert not comms.enforce_enabled()


def test_program_counts_none_without_stable_text():
    assert comms.program_counts(object()) is None

    class Raises:
        def as_text(self):
            raise RuntimeError("no text for you")

    class NotText:
        def as_text(self):
            return 7

    assert comms.program_counts(Raises()) is None
    assert comms.program_counts(NotText()) is None

    class Texty:
        def as_text(self):
            return HLO_FIXTURE

    assert comms.program_counts(Texty())["total"] == 6


# --------------------------------------------------- the engine gate


def _bank(k=4, s=5, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _mesh_engine(tmp_path, **kw):
    d = _bank()
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=2, tol=0.0,
        verbose="none",
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=10.0,
        metrics_dir=str(tmp_path), verbose="none", mesh_shape=(2,),
        **kw,
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)


def _inject_counts(monkeypatch, n=2):
    injected = {"all_gather": 0, "all_reduce": n, "all_to_all": 0,
                "collective_permute": 0, "total": n}
    monkeypatch.setattr(
        comms, "program_counts", lambda program: dict(injected)
    )
    return injected


@needs2
def test_engine_refuses_injected_collective(tmp_path, monkeypatch):
    """A batch-only mesh program that 'lowers' with a collective in it
    (injected at the counting seam) must never finish warmup."""
    _inject_counts(monkeypatch)
    with pytest.raises(comms.CommBudgetError, match="batch-only"):
        _mesh_engine(tmp_path)


@needs2
def test_engine_records_failing_verdict_unenforced(
    tmp_path, monkeypatch,
):
    """CCSC_COMM_BUDGET_ENFORCE=0: the over-budget engine builds and
    serves, but the comm_audit event records ok=False with the real
    per-class counts — observable, never hidden."""
    monkeypatch.setenv("CCSC_COMM_BUDGET_ENFORCE", "0")
    injected = _inject_counts(monkeypatch)
    eng = _mesh_engine(tmp_path)
    try:
        assert all(
            c["total"] == injected["total"]
            for c in eng.comm_counts.values()
        )
    finally:
        eng.close()
    audits = [
        e for e in obs.read_events(str(tmp_path))
        if e.get("type") == "comm_audit"
    ]
    assert audits, "mesh warmup must emit comm_audit per bucket"
    assert all(e["ok"] is False for e in audits)
    assert all(e["budget"] == 0 for e in audits)
    assert all(e["total"] == injected["total"] for e in audits)
    assert all(e["all_reduce"] == injected["all_reduce"] for e in audits)
