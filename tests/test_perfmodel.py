"""Utilization model sanity: XLA's cost analysis vs the analytic count.

The analytic model is the fallback for backends without cost_analysis
(the axon tunnel); it must agree with XLA's own count to well within an
order of magnitude or the reported MFU is meaningless.
"""
import jax
import jax.numpy as jnp
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
from ccsc_code_iccv2017_tpu.parallel import consensus
from ccsc_code_iccv2017_tpu.utils import perfmodel


def test_analytic_vs_xla_cost():
    blocks, ni, k, size = 2, 4, 8, 24
    geom = ProblemGeom((5, 5), k)
    cfg = LearnConfig(
        max_it=1, max_it_d=3, max_it_z=5, num_blocks=blocks,
        rho_d=500.0, rho_z=10.0, verbose="none",
    )
    fg = common.FreqGeom.create(geom, (size, size))
    state = learn_mod.init_state(
        jax.random.PRNGKey(0), geom, fg, blocks, ni
    )
    b_blocks = jax.random.normal(
        jax.random.PRNGKey(1), (blocks, ni, size, size), jnp.float32
    )
    step = consensus.make_outer_step(geom, cfg, fg, mesh=None)
    compiled = step.lower(state, b_blocks).compile()
    xla = perfmodel.compiled_cost(compiled)
    if xla is None:
        pytest.skip("backend has no cost_analysis")
    ana = perfmodel.analytic_outer_step_cost(
        num_blocks=blocks, ni=ni, k=k, spatial=fg.spatial_shape,
        num_freq=fg.num_freq, max_it_d=cfg.max_it_d,
        max_it_z=cfg.max_it_z,
    )
    ratio_f = ana["flops"] / xla["flops"]
    assert 0.2 < ratio_f < 5.0, (ana, xla)
    # bytes: analytic is a minimal-traffic lower-bound style estimate;
    # allow a wider band but the same order of magnitude
    ratio_b = ana["bytes"] / xla["bytes"]
    assert 0.1 < ratio_b < 10.0, (ana, xla)


def test_utilization_fields():
    u = perfmodel.utilization(
        {"flops": 1e12, "bytes": 1e9}, steps_per_sec=2.0, chip="v5e"
    )
    assert u["achieved_tflops"] == pytest.approx(2.0)
    assert u["mfu_vs_bf16_peak"] == pytest.approx(2e12 / 197e12)
    assert u["achieved_gbps"] == pytest.approx(2.0)
    assert u["hbm_frac"] == pytest.approx(2e9 / 819e9)


def test_detect_chip_cpu():
    # under the test conftest the backend is CPU; a CPU run must never
    # be scored against a TPU roofline even with the axon env set
    assert perfmodel.detect_chip() == "cpu"
