"""The quality observatory (serve.quality) — ISSUE 18.

Contracts under test:
- ONE shared valid-region PSNR: the engine's delivered ``res.psnr``,
  the capture outcome record and every scorer quote the exact same
  :func:`quality.valid_region_psnr` value (bit-equality pinned here
  against a real capture);
- dB histograms: the shared 0.5 dB bucket table, per-(bank, tenant,
  bucket) folding, ``unit: db`` snapshots;
- tenant quality floors: a breach fires only when the median-rank
  bucket's UPPER edge is provably below ``min_psnr_db``, with the
  SloMonitor re-fire dedup (a breached-and-idle tenant is silent);
- drift watch: one ``quality_drift`` fire per excursion against a
  per-(bank, digest) band, band lookup cached (including the
  no-history negative);
- solve diagnostics ride the EXISTING dispatch fence: equal dispatch
  counts and bit-identical recons with ``track_diagnostics`` off/on;
- golden probes: deterministic generation (idempotent regenerate),
  self-sealing references, bit-exact re-judgment, and the bank-rot
  guard — a never-seen digest that regresses the bank's STANDING
  reference is judged ``regressed``, never blessed as its own
  baseline (including across bank ids sharing a digest);
- shadow scoring: ``score_bank`` appends ``kind=quality`` ledger
  records keyed identically across banks with the candidate's
  content digest as a record FIELD; ``judge_candidate`` /
  ``gate_publish`` split live-vs-candidate by that field;
- ``scripts/quality_gate.py`` exit contract: 0 clean, 1 regression,
  2 usage (unknown candidate);
- the serving fleet schedules probes through idle capacity and the
  capture store never records probe traffic.
"""
import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.analysis import ledger as ledger_mod
from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
    TenantSpec,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import (
    CodecEngine,
    ServeFleet,
    capture as capture_mod,
    quality,
    registry as registry_mod,
)
from ccsc_code_iccv2017_tpu.utils import obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bank(k=4, s=3, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _geom():
    return ProblemGeom(spatial_support=(3, 3), num_filters=4)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none",
    )
    base.update(kw)
    return SolveConfig(**base)


def _scfg(**kw):
    base = dict(
        buckets=((2, (8, 8)),), max_wait_ms=2.0, verbose="none",
    )
    base.update(kw)
    return ServeConfig(**base)


def _engine(d=None, cfg=None, scfg=None):
    return CodecEngine(
        d if d is not None else _bank(),
        ReconstructionProblem(_geom()),
        cfg or _cfg(),
        scfg or _scfg(),
    )


def _req(seed, side=8):
    r = np.random.default_rng(seed)
    x = r.random((side, side)).astype(np.float32)
    return x


# ---------------------------------------------------------------------
# the shared valid-region PSNR
# ---------------------------------------------------------------------


def test_valid_region_psnr_matches_manual_crop():
    r = np.random.default_rng(0)
    rec = r.random((8, 8)).astype(np.float32)
    ref = r.random((8, 8)).astype(np.float32)
    got = quality.valid_region_psnr(rec, ref, (1, 1))
    mse = float(np.mean((rec[1:-1, 1:-1] - ref[1:-1, 1:-1]) ** 2))
    assert got == pytest.approx(10 * np.log10(1.0 / mse))
    # perfect reconstruction is finite (mse floor), not inf
    assert np.isfinite(quality.valid_region_psnr(ref, ref, (1, 1)))


def test_capture_recorded_psnr_is_bit_equal_to_shared_fn(tmp_path):
    """The satellite pin: the dB the capture outcome records IS
    round(valid_region_psnr(recon, x_orig, psf_radius), 6) — replay
    and the shadow scorer recompute with the same function, so the
    two can never drift."""
    cdir = str(tmp_path / "cap")
    geom = _geom()
    eng = _engine(
        cfg=_cfg(track_psnr=True),
        scfg=_scfg(capture_dir=cdir),
    )
    try:
        xs = [_req(i) for i in range(3)]
        results = [
            eng.reconstruct(x, x_orig=x, timeout=180) for x in xs
        ]
    finally:
        eng.close()
    for x, res in zip(xs, results):
        want = quality.valid_region_psnr(
            np.asarray(res.recon), x, geom.psf_radius
        )
        assert res.psnr == pytest.approx(want, abs=0)
    entries = capture_mod.read_workload(cdir)
    assert len(entries) == 3
    by_sha = {
        e["x_orig"]: e["outcome"] for e in entries if e.get("x_orig")
    }
    assert len(by_sha) == 3
    for x, res in zip(xs, results):
        out = by_sha[capture_mod.payload_sha(x)]
        assert out is not None
        recon = np.ascontiguousarray(
            np.asarray(res.recon, np.float32)
        )
        assert out["digest"] == capture_mod.payload_sha(recon)
        # bit-equality, not approx: both sides are the one shared
        # function rounded the one shared way
        assert out["psnr"] == round(
            quality.valid_region_psnr(recon, x, geom.psf_radius), 6
        )


# ---------------------------------------------------------------------
# dB histograms + tenant floors
# ---------------------------------------------------------------------


def test_db_bounds_table_shape():
    b = quality.DB_BOUNDS
    assert b[0] == 0.5 and b[-1] == 80.0
    steps = {round(hi - lo, 6) for lo, hi in zip(b, b[1:])}
    assert steps == {0.5}


def test_monitor_db_bucketing_and_snapshots():
    m = quality.QualityMonitor(check_s=0.0)
    for db in (20.2, 20.2, 35.0):
        assert m.observe(
            db, bank_id="bk", tenant="t", bucket="8x8"
        ) == []
    # untracked / nonfinite observations are no-ops
    m.observe(None, bank_id="bk", tenant="t", bucket="8x8")
    m.observe(float("nan"), bank_id="bk", tenant="t", bucket="8x8")
    snaps = m.raw_snapshots()
    assert len(snaps) == 1
    sn = snaps[0]
    assert (sn["bank_id"], sn["tenant"], sn["bucket"]) == (
        "bk", "t", "8x8",
    )
    assert sn["unit"] == "db" and sn["n"] == 3
    # median rank bucket is (20.0, 20.5]: upper edge, dB semantics
    assert sn["p50_ms"] == 20.5


def test_floor_breach_upper_edge_refire_dedup_and_recovery():
    spec = TenantSpec(tenant="t", min_psnr_db=30.0)
    m = quality.QualityMonitor(specs=[spec], check_s=0.0)
    # floor INSIDE the median bucket (29.5, 30.0] must not breach:
    # upper edge 30.0 is not provably below 30.0
    for db in (29.6, 29.8, 30.4):
        m.observe(db, tenant="t", bucket="8x8")
    br, snaps, _ = m.tick()
    assert br == [] and len(snaps) == 1 and m.n_breached == 0
    # provably below: every observation under (28.5, 29.0]
    for db in (28.9, 28.9, 28.9):
        m.observe(db, tenant="t", bucket="8x8")
    br, _, _ = m.tick()
    assert len(br) == 1
    assert br[0]["tenant"] == "t"
    assert br[0]["min_psnr_db"] == 30.0
    assert br[0]["observed_db"] < 30.0
    assert m.n_breached == 1
    # re-fire dedup: no new observations -> no second fire
    br, _, _ = m.tick()
    assert br == []
    assert m.n_breached == 1
    # one more low observation re-arms the judgment
    m.observe(28.9, tenant="t", bucket="8x8")
    br, _, _ = m.tick()
    assert len(br) == 1
    # recovery: pull the median well above the floor
    for _ in range(20):
        m.observe(36.2, tenant="t", bucket="8x8")
    br, _, _ = m.tick()
    assert br == [] and m.n_breached == 0


def test_monitor_tick_cadence_and_final_flush():
    m = quality.QualityMonitor(check_s=3600.0)
    m.observe(25.0, bank_id=None, tenant=None, bucket="8x8")
    assert m.tick() != ([], [], [])  # first tick always flushes
    m.observe(26.0, bank_id=None, tenant=None, bucket="8x8")
    assert m.tick() == ([], [], [])  # inside the cadence window
    _, snaps, _ = m.final()  # close flush is unconditional
    assert len(snaps) == 1 and snaps[0]["n"] == 2


def test_drift_watch_fires_once_per_excursion_and_caches_band():
    calls = []
    band = quality.quality_band([30.0] * 5, db=1.0)
    assert band is not None and band["lo"] == pytest.approx(29.0)

    def band_for(bank_id, digest):
        calls.append((bank_id, digest))
        return band if digest == "dg" else None

    m = quality.QualityMonitor(
        check_s=0.0, drift_band_for=band_for, drift_window=3
    )
    fires = []
    for _ in range(4):
        fires += m.observe(
            28.0, bank_id="bk", digest="dg", bucket="8x8"
        )
    # window fills at 3, fires once, stays silent while low
    assert len(fires) == 1
    f = fires[0]
    assert f["bank_id"] == "bk" and f["digest"] == "dg"
    assert f["rolling_db"] < f["band_lo"] == pytest.approx(29.0)
    assert f["window"] == 3
    # recovery re-arms, a second excursion fires again
    for _ in range(3):
        assert m.observe(31.0, bank_id="bk", digest="dg") == []
    fires2 = []
    for _ in range(3):
        fires2 += m.observe(28.0, bank_id="bk", digest="dg")
    assert len(fires2) == 1
    # one band lookup per (bank, digest) — cached, not per request
    assert calls.count(("bk", "dg")) == 1
    # the no-history negative is cached too
    for _ in range(3):
        m.observe(28.0, bank_id="bk", digest="other")
    assert calls.count(("bk", "other")) == 1
    # no digest -> no drift machinery at all
    assert m.observe(20.0, bank_id="bk") == []


def test_quality_band_absolute_db_floor():
    # tight history: the MAD term is tiny, the dB floor binds
    band = quality.quality_band([30.0, 30.05, 29.95], db=1.0)
    assert band["lo"] == pytest.approx(29.0)
    # wide history: the MAD term binds past the floor
    wide = quality.quality_band(
        [25.0, 30.0, 35.0, 20.0, 40.0], db=1.0
    )
    assert wide["lo"] < wide["median"] - 1.0
    assert quality.quality_band([]) is None


# ---------------------------------------------------------------------
# solve diagnostics ride the existing fence
# ---------------------------------------------------------------------


def test_solve_diag_fence_parity_and_obj_split():
    """The fence-parity assertion: turning diagnostics on adds ZERO
    dispatches (the extras subtree rides the result pytree of the
    dispatch already paid for) and changes no served bit."""
    xs = [_req(i) for i in range(4)]
    outs = {}
    stats = {}
    diags = {}
    for flag in (False, True):
        eng = _engine(cfg=_cfg(track_diagnostics=flag))
        try:
            outs[flag] = [
                np.asarray(
                    eng.reconstruct(x, timeout=180).recon
                )
                for x in xs
            ]
            stats[flag] = eng.stats()["n_dispatches"]
        finally:
            diags[flag] = eng._quality.final()[2]
            eng.close()
    assert stats[True] == stats[False]
    for a, b in zip(outs[False], outs[True]):
        assert np.array_equal(a, b)
    # iteration accounting is always on; the objective split only
    # exists when the solve actually tracked it on device
    for flag in (False, True):
        assert len(diags[flag]) == 1
        assert diags[flag][0]["n"] == len(xs)
    assert "obj_fid_mean" not in diags[False][0]
    assert "obj_fid_mean" in diags[True][0]
    assert "obj_l1_mean" in diags[True][0]
    assert diags[True][0]["nonfinite"] == 0
    d = diags[True][0]
    assert d["tol_stop_frac"] + d["maxit_stop_frac"] == pytest.approx(
        1.0
    )


# ---------------------------------------------------------------------
# golden probes
# ---------------------------------------------------------------------


def test_synth_probe_deterministic_and_unit_peak():
    d = np.asarray(_bank(), np.float32)
    a = quality.synth_probe(d, (8, 8), seed=7)
    b = quality.synth_probe(d, (8, 8), seed=7)
    assert np.array_equal(a, b)
    assert a.dtype == np.float32 and a.shape == (8, 8)
    assert np.abs(a).max() == pytest.approx(1.0, abs=1e-5)
    c = quality.synth_probe(d, (8, 8), seed=8)
    assert not np.array_equal(a, c)


def test_probe_generate_idempotent_and_persistent(tmp_path):
    pdir = str(tmp_path / "probes")
    d = np.asarray(_bank(), np.float32)
    ps = quality.ProbeSet.generate(
        pdir, _geom(), ((2, (8, 8)),), n_per_bucket=2, d=d
    )
    assert len(ps) == 2
    names = [p["name"] for p in ps.probes()]
    manifest = open(os.path.join(pdir, ps.MANIFEST)).read()
    # regenerate: nothing re-recorded, probes identical
    ps2 = quality.ProbeSet.generate(
        pdir, _geom(), ((2, (8, 8)),), n_per_bucket=2, d=d
    )
    assert [p["name"] for p in ps2.probes()] == names
    assert open(os.path.join(pdir, ps.MANIFEST)).read() == manifest
    for p in ps2.probes():
        x = ps2.load(p["x_orig"])
        assert np.array_equal(
            x, ps2.load(p["b"])
        )  # synth probes serve unmasked
        assert p["psf_radius"] == [1, 1]


def test_probe_reference_seals_then_judges_exact(tmp_path):
    pdir = str(tmp_path / "probes")
    d = _bank()
    eng = _engine(d=d)
    try:
        ps = quality.ProbeSet.generate(
            pdir, _geom(), ((2, (8, 8)),),
            d=np.asarray(d, np.float32),
        )
        first = ps.run(eng, timeout=180)
        assert [v["status"] for v in first] == ["reference"]
        dg = first[0]["digest"]
        assert dg == eng.bank_digest()
        assert ps.reference(first[0]["probe"], dg) is not None
        # the same digest re-served is bit-exact against its sealed
        # reference — and a RELOADED set judges identically
        again = ps.run(eng, timeout=180)
        assert [v["status"] for v in again] == ["exact"]
        reloaded = quality.ProbeSet(pdir)
        assert [
            v["status"] for v in reloaded.run(eng, timeout=180)
        ] == ["exact"]
    finally:
        eng.close()


class _FakeTarget:
    """A reconstruct/bank_digest shim: ProbeSet.run needs nothing
    else, which keeps the rot-guard truth table exact and fast."""

    def __init__(self, digest, degrade=0.0, seed=3):
        self._digest = digest
        self._degrade = float(degrade)
        self._rng = np.random.default_rng(seed)

    def bank_digest(self, bank_id=None):
        return self._digest

    def reconstruct(
        self, b, mask=None, x_orig=None, bank_id=None, timeout=None
    ):
        noise = np.random.default_rng(0).standard_normal(
            b.shape
        ).astype(np.float32)
        recon = b + (0.001 + self._degrade) * noise

        class _R:
            pass

        r = _R()
        r.recon = recon.astype(np.float32)
        return r


def test_probe_bank_rot_guard_and_standing_reference_link(tmp_path):
    """The guard truth table: a digest the bank never served may
    self-seal only when it does NOT regress the bank's standing
    reference — including when that reference was first sealed under
    a different bank id sharing the digest (the link rule)."""
    pdir = str(tmp_path / "probes")
    ps = quality.ProbeSet.generate(
        pdir, _geom(), ((1, (8, 8)),), seed=5,
        d=np.asarray(_bank(), np.float32),
    )
    name = ps.probes()[0]["name"]
    good = _FakeTarget("dg-good", degrade=0.0)
    rot = _FakeTarget("dg-rot", degrade=0.3)  # several dB worse
    peer = _FakeTarget("dg-peer", degrade=0.0)

    # 1. the DEFAULT bank seals the good digest's reference
    assert ps.run(good)[0]["status"] == "reference"
    # 2. bank id "bk" serves the SAME digest: judged exact, and the
    #    reference is linked as bk's standing baseline
    v = ps.run(good, bank_id="bk")
    assert v[0]["status"] == "exact"
    # 3. a never-seen digest that regresses bk's standing reference
    #    is judged regressed — NOT blessed as its own baseline
    v = ps.run(rot, bank_id="bk")
    assert v[0]["status"] == "regressed"
    assert v[0]["ref_db"] is not None
    assert v[0]["db"] < float(v[0]["ref_db"]) - v[0]["db_tol"]
    assert ps.reference(name, "dg-rot") is None
    # ... and the verdict survives a reload (the link was persisted)
    assert (
        quality.ProbeSet(pdir).run(rot, bank_id="bk")[0]["status"]
        == "regressed"
    )
    # 4. swapping back to the referenced digest re-judges bit-exact
    assert ps.run(good, bank_id="bk")[0]["status"] == "exact"
    # 5. a never-seen digest that does NOT regress may seal its own
    assert (
        ps.run(peer, bank_id="bk")[0]["status"] == "reference"
    )
    assert ps.reference(name, "dg-peer") is not None


def test_resolve_probe_dir_chain(monkeypatch):
    monkeypatch.delenv("CCSC_PROBE_DIR", raising=False)
    assert quality.resolve_probe_dir(None) is None
    assert quality.resolve_probe_dir("/x") == "/x"
    monkeypatch.setenv("CCSC_PROBE_DIR", "/envd")
    assert quality.resolve_probe_dir(None) == "/envd"
    assert quality.resolve_probe_dir("/x") == "/x"
    # explicit empty string is OFF regardless of the env
    assert quality.resolve_probe_dir("") is None


# ---------------------------------------------------------------------
# fleet integration: probe scheduling + capture probe-skip
# ---------------------------------------------------------------------


def test_fleet_probe_schedule_events_and_capture_skip(tmp_path):
    mdir = str(tmp_path / "metrics")
    pdir = str(tmp_path / "probes")
    cdir = str(tmp_path / "cap")
    interval = 0.25
    fleet = ServeFleet(
        _bank(),
        ReconstructionProblem(_geom()),
        _cfg(),
        _scfg(),
        FleetConfig(
            replicas=1, metrics_dir=mdir, min_queue_depth=64,
            restart_backoff_s=0.05, verbose="none",
            capture_dir=cdir,
            probe_dir=pdir, probe_interval_s=interval,
        ),
    )
    try:
        x = _req(1)
        fleet.submit(x, x_orig=x, key="real-0").result(timeout=180)
        # idle fleet: the probe thread must sweep on its own clock
        deadline = time.time() + 40 * interval
        probed = []
        while time.time() < deadline:
            probed = [
                e
                for e in obs.read_events(mdir, recursive=True)
                if e.get("type") == "quality_probe"
            ]
            if len(probed) >= 2:
                break
            time.sleep(interval / 2)
    finally:
        fleet.close()
    assert len(probed) >= 2
    # first sweep seals, later sweeps are bit-exact on an unchanged
    # bank — never a breach
    statuses = [e["status"] for e in probed]
    assert statuses[0] == "reference"
    assert set(statuses) <= {"reference", "exact", "db_ok"}
    assert fleet.metrics()["counters"]["probe_failures_total"] == 0
    assert fleet.quality_advice() == []
    ps = quality.ProbeSet(pdir)
    assert len(ps) >= 1
    # probe traffic is NOT captured workload: replaying the capture
    # must reproduce the real request stream only
    keys = [e["key"] for e in capture_mod.read_workload(cdir)]
    assert keys == ["real-0"]
    assert not any(
        k.startswith(quality.PROBE_KEY_PREFIX) for k in keys
    )


# ---------------------------------------------------------------------
# shadow scoring + the gate
# ---------------------------------------------------------------------


def _seed_quality_ledger(path, live_digest, values, bank="default"):
    led = ledger_mod.Ledger(path)
    for v in values:
        rec = ledger_mod.normalize_record(
            chip="testchip", kind="quality", value=float(v),
            unit="db", workload="w", shape_key="sk",
            knobs={"bank": bank}, source="test",
        )
        rec.update(digest=live_digest)
        led.append(rec)
    return led


def test_score_bank_ledger_keying_by_digest(tmp_path):
    cdir = str(tmp_path / "cap")
    lpath = str(tmp_path / "led.jsonl")
    d_live = _bank(seed=0)
    d_cand = _bank(seed=9)
    eng = _engine(
        d=d_live,
        cfg=_cfg(track_psnr=True),
        scfg=_scfg(capture_dir=cdir),
    )
    try:
        for i in range(3):
            x = _req(10 + i)
            eng.reconstruct(x, x_orig=x, timeout=180)
    finally:
        eng.close()
    rec_live = quality.score_bank(
        cdir, d_live, ledger_path=lpath, timeout=180
    )
    rec_cand = quality.score_bank(
        cdir, d_cand, ledger_path=lpath, timeout=180
    )
    assert rec_live["kind"] == rec_cand["kind"] == "quality"
    assert rec_live["unit"] == "db"
    assert rec_live["digest"] == registry_mod.bank_digest(d_live)
    assert rec_cand["digest"] == registry_mod.bank_digest(d_cand)
    assert rec_live["digest"] != rec_cand["digest"]
    assert rec_live["knobs"] == {"bank": "default"}
    assert rec_live["n_scored"] == 3
    assert rec_live["min_db"] <= rec_live["p10_db"]
    # both banks land under ONE ledger key: the digest is a record
    # field the gate partitions by, never part of the key
    led = ledger_mod.Ledger(lpath)
    keys = {
        k
        for k, rows in led.by_key().items()
        if any(r.get("kind") == "quality" for r in rows)
    }
    assert len(keys) == 1


def test_judge_candidate_and_gate_publish(tmp_path):
    lpath = str(tmp_path / "led.jsonl")
    led = _seed_quality_ledger(
        lpath, "dg-live", [30.0, 30.1, 29.9]
    )
    for dg, val in (("dg-ok", 29.8), ("dg-bad", 25.0)):
        rec = ledger_mod.normalize_record(
            chip="testchip", kind="quality", value=val, unit="db",
            workload="w", shape_key="sk",
            knobs={"bank": "default"}, source="test",
        )
        rec.update(digest=dg)
        led.append(rec)
    led = ledger_mod.Ledger(lpath)
    ok = quality.judge_candidate(led, "dg-ok", db=1.0)
    assert len(ok) == 1 and ok[0]["ok"] and not ok[0]["skipped"]
    # live history = every record under another digest (3 seeded
    # live records + the other candidate's score)
    assert ok[0]["n_history"] == 4
    bad = quality.judge_candidate(led, "dg-bad", db=1.0)
    assert len(bad) == 1 and not bad[0]["ok"]
    assert bad[0]["value"] == 25.0 and bad[0]["lo"] > 25.0
    # unknown digest: nothing to judge
    assert quality.judge_candidate(led, "dg-nope") == []
    # thin live history is a trivial pass, reported as skipped
    thin = quality.judge_candidate(
        led, "dg-bad", db=1.0, min_history=10
    )
    assert thin[0]["skipped"] and thin[0]["ok"]
    # the publish guard raises on the regression verdict only
    assert quality.gate_publish("dg-ok", ledger_path=lpath)
    with pytest.raises(quality.QualityGateError) as ei:
        quality.gate_publish("dg-bad", ledger_path=lpath)
    assert ei.value.verdicts and not ei.value.verdicts[0]["ok"]


def test_quality_gate_cli_exit_codes(tmp_path):
    lpath = str(tmp_path / "led.jsonl")
    led = _seed_quality_ledger(
        lpath, "dg-live", [30.0, 30.1, 29.9]
    )
    for dg, val in (("dg-ok", 29.8), ("dg-bad", 25.0)):
        rec = ledger_mod.normalize_record(
            chip="testchip", kind="quality", value=val, unit="db",
            workload="w", shape_key="sk",
            knobs={"bank": "default"}, source="test",
        )
        rec.update(digest=dg)
        led.append(rec)

    def gate(*args):
        return subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "quality_gate.py"),
                "--ledger", lpath, *args,
            ],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=120,
        )

    r = gate("--candidate", "dg-ok", "--db", "1.0")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 regression(s)" in r.stdout
    r = gate("--candidate", "dg-bad", "--db", "1.0")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    r = gate("--candidate", "dg-absent")
    assert r.returncode == 2, r.stdout + r.stderr
    r = gate()  # no candidate, no --list: usage
    assert r.returncode == 2
    r = gate("--list")
    assert r.returncode == 0 and "dg-live" in r.stdout
