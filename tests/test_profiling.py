"""Profiling subsystem: XLA trace capture and section timers
(utils/profiling.py — the profiler integration the reference lacks,
SURVEY.md section 5)."""
import os

import jax
import jax.numpy as jnp
import numpy as np

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import learn as learn_mod
from ccsc_code_iccv2017_tpu.utils import profiling


def test_section_timers():
    t = profiling.SectionTimers()
    with t.section("a"):
        pass
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    rep = t.report()
    assert set(rep) == {"a", "b"}
    assert t.counts["a"] == 2 and rep["a"] >= 0.0
    assert "a=" in str(t)


def test_xla_trace_none_is_noop():
    with profiling.xla_trace(None):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_learn_with_profile_dir(tmp_path):
    prof = str(tmp_path / "prof")
    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    geom = ProblemGeom((3, 3), 4)
    cfg = LearnConfig(
        max_it=1, max_it_d=1, max_it_z=1, num_blocks=2,
        rho_d=50.0, rho_z=2.0, verbose="none",
    )
    res = learn_mod.learn(
        jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0),
        profile_dir=prof,
    )
    assert res.d.shape == (4, 3, 3)
    # the capture must have produced xplane artifacts
    found = [
        f
        for _, _, fs in os.walk(prof)
        for f in fs
        if f.endswith((".xplane.pb", ".trace.json.gz", ".json.gz"))
    ]
    assert found, f"no profiler artifacts under {prof}"


def test_verbose_all_writes_figures(tmp_path, capsys):
    """verbose='all' produces per-iteration figures (the reference's
    display_func behavior, dParallel.m:326-369, headless)."""
    figs = str(tmp_path / "figs")
    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    geom = ProblemGeom((3, 3), 4)
    cfg = LearnConfig(
        max_it=2, max_it_d=1, max_it_z=1, num_blocks=2,
        rho_d=50.0, rho_z=2.0, tol=0.0, verbose="all",
    )
    learn_mod.learn(
        jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0),
        figures_dir=figs,
    )
    capsys.readouterr()
    files = sorted(os.listdir(figs))
    assert "filters_001.png" in files and "filters_002.png" in files
    assert "iterates_001.png" in files and "iterates_002.png" in files
