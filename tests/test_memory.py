"""Masked-learner memory story: analytic HBM estimator, pre-flight
warning, and machine-readable algorithm identity in traces/.mat files
(VERDICT r2 weak #6)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import learn_masked as lm


def test_hbm_estimate_scales():
    geom = ProblemGeom((11, 11), 100, (31,))
    small = lm.hbm_estimate(geom, (64, 64), n=4)
    big = lm.hbm_estimate(geom, (64, 64), n=16)
    assert big["total_bytes"] > small["total_bytes"]
    # the d-pass Woodbury term grows quadratically in n
    assert big["woodbury_bytes"] > 4 * small["woodbury_bytes"]
    # frequency sharding shrinks solve temporaries, not state
    sharded = lm.hbm_estimate(geom, (64, 64), n=4, num_freq_shards=4)
    assert sharded["state_bytes"] == small["state_bytes"]
    assert sharded["woodbury_bytes"] < small["woodbury_bytes"]


def test_hbm_estimate_order_of_magnitude():
    # the reference HS operating point (learn_hyperspectral.m:3): kernel
    # [11,11,31,100]; a handful of 128^2 cubes must estimate in the
    # tens-of-GB range that motivated the memory story
    geom = ProblemGeom((11, 11), 100, (31,))
    est = lm.hbm_estimate(geom, (128, 128), n=10)
    assert 1e9 < est["total_bytes"] < 1e12


def test_algorithm_identity_in_traces(tmp_path):
    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.parallel.streaming import learn_streaming
    from ccsc_code_iccv2017_tpu.utils.io_mat import _loadmat, save_filters

    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16), jnp.float32)
    )
    geom = ProblemGeom((5, 5), 4)
    cfg = LearnConfig(
        max_it=1, max_it_d=2, max_it_z=2, num_blocks=2, verbose="none"
    )
    r_mem = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(1))
    assert r_mem.trace["algorithm"] == "consensus"
    r_str = learn_streaming(b, geom, cfg, key=jax.random.PRNGKey(1))
    assert r_str.trace["algorithm"] == "consensus_streaming"

    r_msk = lm.learn_masked(
        jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(1)
    )
    assert r_msk.trace["algorithm"] == "masked_admm"

    # identity survives the .mat round-trip
    out = tmp_path / "f.mat"
    save_filters(str(out), r_msk.d, r_msk.trace, layout="2d")
    loaded = _loadmat(str(out))["iterations"]
    names = (
        loaded.dtype.names
        if loaded.dtype.names
        else loaded[0, 0].dtype.names
    )
    assert "algorithm" in names


def test_preflight_warns_when_over_limit(monkeypatch):
    # force a tiny fake device limit and check the warning fires
    class FakeDev:
        def memory_stats(self):
            return {"bytes_limit": 1_000_000}

    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    geom = ProblemGeom((11, 11), 100, (31,))
    with pytest.warns(UserWarning, match="likely OOM"):
        lm._preflight_hbm(geom, (128, 128), n=10)
