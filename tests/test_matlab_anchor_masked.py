"""MATLAB-anchored golden trajectory for the MASKED (hyperspectral-
family) learner — the third transcription anchor, alongside the
inpainting (test_matlab_anchor.py) and consensus-learner
(test_matlab_anchor_learn.py) anchors.

Literal, line-ordered float64 NumPy transcription of
2-3D/DictionaryLearning/admm_learn.m at sw = 1 (a single "wavelength"),
where the reference's diagonal-approximate W > 1 z-solve (:311-319)
coincides with the exact rank-1 Sherman-Morrison — so the framework's
exact solver (a documented divergence for W > 1, ops/freq_solvers.py
docstring) must match to float tolerance. The anchor pins everything
else the oracle tests can't independently witness: the masked data
prox (:26), the gamma heuristic g = 60 lambda/max(b) with divisors
5000/500 (:36-38), the smooth-init offset plumbing (:19,:25-26,:235),
the d-pass update order with z spectra FIXED through the inner loop
(:100-126), the z-pass order (:165-189), and the zero-dual /
randn-z / replicated-2D-randn-d init (:42-69).

The framework side drives models.learn_masked._outer_step directly
from the same init (the public learn_masked draws its own randn).
The rollback (:204-213) is host-level logic outside the anchored step;
configs here are chosen so it would not fire.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn_masked as lm
from ccsc_code_iccv2017_tpu.ops import fourier


def fft2(x):
    return np.fft.fft2(x, axes=(0, 1))


def ifft2(x):
    return np.fft.ifft2(x, axes=(0, 1))


def kernel_proj(u, r):
    """KernelConstraintProj (:239-253), [sx, sy, k] layout."""
    up = np.roll(u, (r, r), (0, 1))
    up = up[: 2 * r + 1, : 2 * r + 1, :]
    un = np.broadcast_to(
        np.sum(up**2, axis=(0, 1), keepdims=True), up.shape
    )
    up = np.where(un >= 1, up / np.sqrt(np.where(un >= 1, un, 1.0)), up)
    full = np.zeros_like(u)
    full[: 2 * r + 1, : 2 * r + 1, :] = up
    return np.roll(full, (-r, -r), (0, 1))


def solve_conv_term_D(z_hat, xi1_hat, xi2_hat, rho):
    """solve_conv_term_D (:273-300) at sw = 1: per-frequency pinv
    Woodbury, column-major frequency flattening."""
    sx, sy, k, n = z_hat.shape
    ss = sx * sy
    zf = np.reshape(z_hat, (ss, k, n), order="F")  # :285
    x1 = np.reshape(xi1_hat, (ss, n), order="F")  # :283
    x2 = np.reshape(xi2_hat, (ss, k), order="F")  # :284
    out = np.empty((ss, k), complex)
    for f in range(ss):
        A = zf[f].T  # [n, k] (permute [3,2,1])
        opt = (
            np.eye(k)
            - A.conj().T
            @ np.linalg.pinv(rho * np.eye(n) + A @ A.conj().T)
            @ A
        ) / rho  # :290
        out[f] = opt @ (A.conj().T @ x1[f] + rho * x2[f])  # :293
    return np.reshape(out, (sx, sy, k), order="F")  # :298


def solve_conv_term_Z(dhat_flat, dd, xi1_hat, xi2_hat, rho):
    """solve_conv_term_Z (:302-322) at sw = 1: rho = 1 * ratio (:311),
    scalar Sherman-Morrison (:317-319)."""
    sx, sy, k, n = xi2_hat.shape
    ss = sx * sy
    x1 = np.reshape(xi1_hat, (ss, n), order="F")
    x2 = np.reshape(xi2_hat, (ss, k, n), order="F")
    bvec = (
        np.conj(dhat_flat)[:, :, None] * x1[:, None, :] + rho * x2
    )  # :314 (dhatT = conj(dhat))
    sc = 1.0 / (rho + dd)  # :317
    corr = np.einsum("fk,fki->fi", dhat_flat, bvec)
    x = bvec / rho - sc[:, None, None] * np.conj(dhat_flat)[:, :, None] * (
        corr[:, None, :] / rho
    )  # :319 applied exactly (rank-1 form)
    return np.reshape(x, (sx, sy, k, n), order="F")


def matlab_masked_learner(
    b, d0_full, z0, sm, lam_res, lam_pri, max_it, max_it_d, max_it_z, r
):
    """Transcription of the admm_learn.m main loop (:86-226) at sw=1.
    b: [H, W, n]; d0_full: [sx, sy, k] (:54-55 init, already embedded);
    z0: [sx, sy, k, n] (:69); sm: [H, W, n] smooth_init or zeros.
    Returns (obj_vals_d, obj_vals_z) of length max_it each."""
    H, W, n = b.shape
    sx, sy = H + 2 * r, W + 2 * r
    k = d0_full.shape[2]

    smoothinit = np.pad(
        sm, ((r, r), (r, r), (0, 0)), mode="symmetric"
    )  # :19
    M = np.zeros((sx, sy, n))
    M[r : r + H, r : r + W, :] = 1.0  # :257 (M is MtM)
    Bp = np.zeros((sx, sy, n))
    Bp[r : r + H, r : r + W, :] = b
    Mtb = Bp * M - smoothinit * M  # :258

    g = 60.0 * lam_pri / np.max(b)  # :36
    rho_d = 5000.0  # gammas_D(2)/gammas_D(1) (:37,:93)
    rho_z = 500.0  # sw * gammas_Z(2)/gammas_Z(1) at sw=1 (:38,:311)
    theta_d = lam_res / (g / 5000.0)  # :112
    theta_z1 = lam_res / (g / 500.0)  # :175
    theta_z2 = lam_pri / g  # :176

    def prox_data(u, theta):  # :26
        return (Mtb + u / theta) / (M + 1.0 / theta)

    def prox_sparse(u, theta):  # :29
        with np.errstate(divide="ignore", invalid="ignore"):
            f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
        return np.maximum(0.0, f) * u

    def objective(z, d_hat):  # :326-343 (sw=1: z2 == z)
        zhat = fft2(z)
        Dz = np.real(
            ifft2(np.sum(d_hat[:, :, :, None] * zhat, axis=2))
        ) + smoothinit  # :334
        crop = Dz[r : sx - r, r : sy - r, :]
        f_z = lam_res * 0.5 * np.sum((crop - b) ** 2)  # :336
        return f_z + lam_pri * np.sum(np.abs(z))  # :338

    d = d0_full.copy()
    d_hat = fft2(d)  # :57
    z = z0.copy()
    d_D1 = np.zeros((sx, sy, n))  # :46
    d_D2 = np.zeros((sx, sy, k))
    d_Z1 = np.zeros((sx, sy, n))  # :66
    d_Z2 = np.zeros((sx, sy, k, n))

    obj_vals_d, obj_vals_z = [], []
    for _ in range(max_it):  # :86
        z_hat5 = fft2(z)  # :100 — FIXED through the whole d-loop
        for _i_d in range(max_it_d):  # :102
            v1 = np.real(
                ifft2(np.sum(d_hat[:, :, :, None] * z_hat5, axis=2))
            )  # :108
            v2 = d  # :109
            u1 = prox_data(v1 - d_D1, theta_d)  # :112
            u2 = kernel_proj(v2 - d_D2, r)  # :113
            d_D1 = d_D1 - (v1 - u1)  # :117
            d_D2 = d_D2 - (v2 - u2)
            xi1_hat = fft2(u1 + d_D1)  # :120-121
            xi2_hat = fft2(u2 + d_D2)
            d_hat = solve_conv_term_D(z_hat5, xi1_hat, xi2_hat, rho_d)  # :125
            d = np.real(ifft2(d_hat))  # :126
        obj_vals_d.append(objective(z, d_hat))  # :132,:139

        dhat_flat = np.reshape(d_hat, (sx * sy, k), order="F")  # :266
        dd = np.sum(np.conj(dhat_flat) * dhat_flat, axis=1).real  # :267
        z_hat = fft2(z)  # :158
        for _i_z in range(max_it_z):  # :165
            v1 = np.real(
                ifft2(np.sum(d_hat[:, :, :, None] * z_hat, axis=2))
            )  # :171
            v2 = z  # :172
            u1 = prox_data(v1 - d_Z1, theta_z1)  # :175
            u2 = prox_sparse(v2 - d_Z2, theta_z2)  # :176
            d_Z1 = d_Z1 - (v1 - u1)  # :180
            d_Z2 = d_Z2 - (v2 - u2)
            xi1_hat = fft2(u1 + d_Z1)  # :183-184
            xi2_hat = fft2(u2 + d_Z2)
            z_hat = solve_conv_term_Z(
                dhat_flat, dd, xi1_hat, xi2_hat, rho_z
            )  # :188
            z = np.real(ifft2(z_hat))  # :189
        obj_vals_z.append(objective(z, d_hat))  # :195,:202

    return np.array(obj_vals_d), np.array(obj_vals_z)


def test_masked_learner_matches_matlab_transcription():
    rng = np.random.default_rng(55)
    H, s, k, n = 8, 3, 3, 2
    r = s // 2
    sx = H + 2 * r
    b = rng.uniform(0.1, 1.0, (H, H, n))
    sm = rng.uniform(0.0, 0.2, (H, H, n))  # nonzero smooth offset
    d0 = rng.normal(size=(s, s, k))  # :54 randn
    d0_full = np.zeros((sx, sx, k))
    d0_full[:s, :s, :] = d0
    d0_full = np.roll(d0_full, (-r, -r), (0, 1))  # :55
    z0 = rng.normal(size=(sx, sx, k, n))  # :69

    max_it, max_it_d, max_it_z = 2, 10, 10  # :79-80 hardcodes 10/10
    ml_d, ml_z = matlab_masked_learner(
        b, d0_full, z0, sm, 1.0, 1.0, max_it, max_it_d, max_it_z, r
    )

    # ---- framework: drive the jitted outer step from the same init --
    geom = ProblemGeom((s, s), k)
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=max_it,
        tol=0.0,
        max_it_d=max_it_d,
        max_it_z=max_it_z,
        verbose="none",
        track_objective=True,
    )
    fg = common.FreqGeom.create(geom, (H, H))
    b_fw = jnp.asarray(np.transpose(b, (2, 0, 1)), jnp.float32)
    sm_fw = jnp.asarray(np.transpose(sm, (2, 0, 1)), jnp.float32)
    b_pad = fourier.pad_spatial(b_fw, geom.psf_radius)
    M_pad = fourier.pad_spatial(jnp.ones_like(b_fw), geom.psf_radius)
    smoothinit = fourier.pad_spatial(
        sm_fw, geom.psf_radius, mode="symmetric"
    )
    state = lm.MaskedLearnState(
        d_full=jnp.asarray(np.moveaxis(d0_full, -1, 0), jnp.float32),
        dual_d1=jnp.zeros((n, sx, sx), jnp.float32),
        dual_d2=jnp.zeros((k, sx, sx), jnp.float32),
        z=jnp.asarray(np.transpose(z0, (3, 2, 0, 1)), jnp.float32),
        dual_z1=jnp.zeros((n, sx, sx), jnp.float32),
        dual_z2=jnp.zeros((n, k, sx, sx), jnp.float32),
    )
    fw_d, fw_z = [], []
    for _ in range(max_it):
        state, obj_d, obj_z, _, _ = lm._outer_step(
            state, b_pad, M_pad, smoothinit,
            geom=geom, cfg=cfg, fg=fg,
            gamma_div_d=5000.0, gamma_div_z=500.0,
        )
        fw_d.append(float(obj_d))
        fw_z.append(float(obj_z))

    np.testing.assert_allclose(fw_d, ml_d, rtol=2e-3)
    np.testing.assert_allclose(fw_z, ml_z, rtol=2e-3)
    # the trajectory must actually descend (no trivial agreement)
    assert ml_z[-1] < 0.8 * ml_d[0]
