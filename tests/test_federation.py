"""Cross-host federated serving (serve.dqueue + serve.federation):

- an in-process federated host serves a frontend's stream with
  results BIT-IDENTICAL to the same requests served by a plain
  in-process fleet (federation adds durability, not numerics);
- the acceptance chaos proof: two federated fleet PROCESSES drain a
  shared queue, one is SIGKILLed mid-attempt while holding leases —
  the survivor reaps and finishes with ZERO lost requests, every
  delivered result bit-identical to the capture oracle's recorded
  outcome digests, and every trace_id reassembles complete with both
  host ownerships visible;
- frontend contract: in-flight resubmit returns the same future,
  spent keys are refused, close resolves leftovers explicitly;
- scripts/obs_report.py renders the FEDERATION section (per-host
  liveness via the --stale-after rule, queue counters, cross-host
  requeues).
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.serve import capture as cap
from ccsc_code_iccv2017_tpu.serve.federation import (
    FederatedFrontend,
    FederatedHost,
)
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils import trace as trace_util

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bank(k=4, sup=3, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, sup, sup)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return d


def _cfgs():
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    return geom, cfg, scfg


def _requests(n, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = r.random((12, 12)).astype(np.float32)
        m = (r.random((12, 12)) < 0.5).astype(np.float32)
        out.append((x * m, m, x))
    return out


def _host(tmp, d, host_id, metrics_sub, **kw):
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )

    geom, cfg, scfg = _cfgs()
    return FederatedHost(
        os.path.join(tmp, "q"), d, ReconstructionProblem(geom), cfg,
        scfg,
        FleetConfig(
            replicas=1, min_queue_depth=64, restart_backoff_s=0.05,
            verbose="none",
        ),
        host=host_id, metrics_dir=os.path.join(tmp, metrics_sub),
        heartbeat_s=0.2, ttl_s=1.0, skew_s=0.2, verbose="none", **kw,
    )


def test_federated_serve_bit_identical_to_plain_fleet(tmp_path):
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet

    d = _bank()
    geom, cfg, scfg = _cfgs()
    reqs = _requests(5)
    # reference: the same bytes through a plain in-process fleet
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(replicas=1, min_queue_depth=64, verbose="none"),
    )
    ref = [
        fleet.reconstruct(b, mask=m, x_orig=x, timeout=180)
        for b, m, x in reqs
    ]
    fleet.close()
    host = _host(str(tmp_path), d, "hostA", "m-host")
    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        metrics_dir=os.path.join(str(tmp_path), "m-fe"),
        verbose="none",
    )
    try:
        futs = [
            fe.submit(b, mask=m, x_orig=x) for b, m, x in reqs
        ]
        res = [f.result(timeout=180) for f in futs]
        fe.seal()
        assert host.serve_until_sealed(timeout=120)
    finally:
        host.close()
        fe.close()
    for got, want in zip(res, ref):
        # federation moved the bytes through the durable queue and a
        # content-addressed result store — and changed NOTHING
        assert np.array_equal(got.recon, want.recon)
        assert got.digest == cap.payload_sha(
            np.ascontiguousarray(np.asarray(want.recon))
        )
        assert got.host == "hostA" and got.attempts == 1
    evs = obs.read_events(str(tmp_path), recursive=True)
    kinds = {e["type"] for e in evs}
    assert {
        "fed_join", "fed_leave", "fed_heartbeat", "dqueue_submit",
        "dqueue_claim", "dqueue_complete",
    } <= kinds
    # every request's trace reassembles complete across the
    # frontend's and the host's streams
    traces = trace_util.assemble(evs)
    for r in res:
        assert traces[r.trace_id].complete


@pytest.mark.parametrize("who", ["frontend"])
def test_frontend_contract(tmp_path, who):
    d = _bank()
    host = _host(str(tmp_path), d, "hostA", "m-host")
    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        verbose="none",
    )
    try:
        b, m, x = _requests(1)[0]
        f1 = fe.submit(b, mask=m, x_orig=x, key="pin")
        # in-flight resubmit of the same key returns the SAME future
        assert fe.submit(b, mask=m, key="pin") is f1
        r1 = f1.result(timeout=180)
        assert r1.key == "pin"
        # a spent key is refused across the whole pool
        with pytest.raises(ValueError):
            fe.submit(b, mask=m, key="pin")
        # leftovers at close get an explicit error, not a hang
        host.close()
        f2 = fe.submit(b, mask=m, key="orphaned")
        fe.close()
        with pytest.raises(RuntimeError):
            f2.result(timeout=5)
        with pytest.raises(RuntimeError):
            fe.submit(b, mask=m)  # closed frontend refuses
    finally:
        host.close()
        fe.close()


def test_frontend_concurrent_same_key_single_item(tmp_path):
    """Two threads submitting the same key concurrently get the SAME
    future and enqueue exactly one durable item (the pending check
    and registration are atomic under the frontend lock)."""
    import threading

    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        verbose="none",
    )
    try:
        b, m, x = _requests(1)[0]
        got = []
        barrier = threading.Barrier(2)

        def go():
            barrier.wait()
            got.append(fe.submit(b, mask=m, key="dup"))

        ts = [threading.Thread(target=go) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(got) == 2 and got[0] is got[1]
        qdir = os.path.join(str(tmp_path), "q", "queue")
        items = [n for n in os.listdir(qdir) if n.endswith(".json")]
        assert len(items) == 1
    finally:
        fe.close()


def test_failed_request_resolves_error_with_complete_trace(tmp_path):
    """A request whose cross-host attempt budget is exhausted gets an
    explicit error Future AND a complete trace — every ownership
    visible with status 'error'/'requeued' (no engine involved: dead
    hosts are simulated with stale queue handles)."""
    import time as _time

    from ccsc_code_iccv2017_tpu.serve.dqueue import DurableQueue

    qdir = os.path.join(str(tmp_path), "q")
    fe = FederatedFrontend(
        qdir, client="fe0",
        metrics_dir=os.path.join(str(tmp_path), "m-fe"),
        verbose="none",
    )
    fe.queue.max_attempts = 1  # item-record budget: one ownership
    ev = []
    ghost = DurableQueue(
        qdir, host="ghost",
        emit=lambda t, **f: ev.append(dict(f, type=t, t=_time.time())),
        ttl_s=0.15, skew_s=0.0,
    )
    reaper = DurableQueue(
        qdir, host="reaper",
        emit=lambda t, **f: ev.append(dict(f, type=t, t=_time.time())),
        ttl_s=0.15, skew_s=0.0,
    )
    try:
        b, m, x = _requests(1)[0]
        fut = fe.submit(b, mask=m, key="doomed")
        ghost.join()
        assert ghost.claim()  # then the "host" dies silently
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            time.sleep(0.1)
            reaper.heartbeat()
            reaper.reap()
            if fut.done():
                break
        with pytest.raises(RuntimeError, match="ownership"):
            fut.result(timeout=1)
    finally:
        fe.close()
    events = obs.read_events(str(tmp_path), recursive=True) + ev
    traces = trace_util.assemble(events)
    (tr,) = traces.values()
    assert tr.complete
    assert tr.root.status == "error"
    attempts = tr.by_name("attempt")
    assert len(attempts) == 1 and attempts[0].status == "error"


def test_whole_host_kill_zero_lost_bit_parity(tmp_path):
    """The ISSUE acceptance: >=2 federated fleet processes serving a
    captured stream; SIGKILL of one FULL PROCESS mid-attempt loses
    zero requests, every delivered result is bit-identical to the
    capture's recorded outcome digests, and every trace_id
    reassembles complete with both host ownerships visible."""
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from scripts.chaos_smoke import _host_kill_child_code

    tmp = str(tmp_path)
    d = _bank()
    geom, cfg, scfg = _cfgs()
    reqs = _requests(8)
    # 1) capture oracle: one unfaulted in-process fleet records the
    # stream's outcome digests
    cap_dir = os.path.join(tmp, "capture")
    fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(
            replicas=1, metrics_dir=os.path.join(tmp, "m-oracle"),
            capture_dir=cap_dir, min_queue_depth=64, verbose="none",
        ),
    )
    for i, (b, m, x) in enumerate(reqs):
        fleet.submit(b, mask=m, x_orig=x, key=f"k{i}")
    fleet.close()
    oracle = {
        rec["key"]: rec["outcome"]["digest"]
        for rec in cap.read_workload(cap_dir)
        if rec.get("outcome")
    }
    assert len(oracle) == len(reqs)
    # 2) two federated fleet PROCESSES; host0 wedges on an injected
    # engine hang while holding leases, then is SIGKILLed whole
    qdir = os.path.join(tmp, "q")
    bank = os.path.join(tmp, "bank.npy")
    np.save(bank, d)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(i, extra=None):
        e = dict(env)
        e.update(extra or {})
        return subprocess.Popen(
            [
                sys.executable, "-c",
                _host_kill_child_code(
                    qdir, bank, os.path.join(tmp, f"m-host{i}"),
                    f"host{i}",
                ),
            ],
            env=e, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )

    p0 = spawn(0, {
        "CCSC_FAULT_ENGINE_HANG_REQ": "3",
        "CCSC_FAULT_ENGINE_HANG_S": "600",
    })
    fe = FederatedFrontend(
        qdir, client="fe0",
        metrics_dir=os.path.join(tmp, "m-frontend"), verbose="none",
    )
    p1 = None
    try:
        futs = [
            fe.submit(b, mask=m, x_orig=x, key=f"fed{i}")
            for i, (b, m, x) in enumerate(reqs)
        ]
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            st = fe.queue.stats()
            if st["results"] >= 1 and st["leased"] >= 1:
                break
            time.sleep(0.05)
        else:
            pytest.fail("host0 never reached the mid-attempt window")
        os.kill(p0.pid, signal.SIGKILL)  # the whole fleet process
        p0.wait()
        p1 = spawn(1)
        fe.seal()
        results = [f.result(timeout=300) for f in futs]
        assert p1.wait(timeout=300) == 0
    finally:
        if p1 is not None and p1.poll() is None:
            p1.kill()
            p1.wait()
        fe.close()
    # zero lost + bit parity vs the capture's recorded digests
    assert len(results) == len(reqs)
    for i, res in enumerate(results):
        assert res.digest == oracle[f"k{i}"], (
            f"request {i}: federated result diverged from the "
            "capture oracle"
        )
    served_by = {res.host for res in results}
    assert "host1" in served_by  # the survivor finished the stream
    handed_off = [r for r in results if r.attempts > 1]
    assert handed_off  # the SIGKILL really cost host0 ownerships
    # 3) the full cross-host story, from the streams alone
    events = obs.read_events(tmp, recursive=True)
    cross = [
        e for e in events
        if e["type"] == "dqueue_requeue"
        and e.get("from_host") == "host0"
        and e.get("by_host") == "host1"
    ]
    assert cross  # survivor reaped the dead host's leases
    traces = trace_util.assemble(events)
    for res in results:
        tr = traces[res.trace_id]
        assert tr.complete, (
            res.key, tr.orphans, tr.unparented,
        )
        attempts = tr.by_name("attempt")
        assert len(attempts) == res.attempts
        if res.attempts > 1:
            # both ownerships visible: the dead host's attempt was
            # written retrospectively by the reaper ('requeued'),
            # the survivor's by its own delivery ('ok')
            statuses = {s.status for s in attempts}
            assert statuses == {"requeued", "ok"}
            span_hosts = {
                e.get("host")
                for e in events
                if e["type"] == "span_end"
                and e.get("trace_id") == res.trace_id
                and e.get("span") == "attempt"
            }
            assert {"host0", "host1"} <= span_hosts
    # 4) the FEDERATION dashboard section renders the casualty
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import obs_report

    text = obs_report.render(events, stale_after=120.0)
    assert "FEDERATION" in text
    assert "host0" in text and "host1" in text
    assert "across hosts" in text


def test_obs_report_federation_staleness(tmp_path):
    """A SIGKILLed host shows up STALE in the FEDERATION liveness
    column by the --stale-after watchdog rule, before its leases even
    expire."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import obs_report

    t0 = 1000.0
    events = [
        {"type": "fed_join", "t": t0, "host": "hA", "epoch": 1},
        {"type": "fed_join", "t": t0, "host": "hB", "epoch": 1},
        {"type": "fed_heartbeat", "t": t0 + 5, "host": "hA",
         "epoch": 1, "served": 3, "leased": 1},
        {"type": "fed_heartbeat", "t": t0 + 400, "host": "hB",
         "epoch": 1, "served": 9, "leased": 0},
        # hC left and was RESTARTED into a fresh epoch: the newer
        # heartbeat must win over the old fed_leave (the supervised
        # restart flow) — hC renders live, not left
        {"type": "fed_join", "t": t0, "host": "hC", "epoch": 1},
        {"type": "fed_leave", "t": t0 + 50, "host": "hC",
         "served": 2},
        {"type": "fed_join", "t": t0 + 60, "host": "hC", "epoch": 2},
        {"type": "fed_heartbeat", "t": t0 + 400, "host": "hC",
         "epoch": 2, "served": 0, "leased": 0},
        {"type": "fed_join", "t": t0, "host": "hD", "epoch": 1},
        {"type": "fed_leave", "t": t0 + 200, "host": "hD",
         "served": 4},
        {"type": "dqueue_submit", "t": t0, "key": "k"},
    ]
    text = obs_report.render(events, stale_after=120.0)
    assert "FEDERATION" in text
    line = lambda h: next(
        ln for ln in text.splitlines() if f"host {h}" in ln
    )
    assert "STALE" in line("hA")
    assert "live" in line("hB")
    assert "live" in line("hC") and "left" not in line("hC")
    assert "left" in line("hD")


def test_frontend_cancel_writes_durable_marker(tmp_path):
    """Cooperative cancellation crosses the host boundary: a frontend
    future cancelled before any claim becomes a durable 'cancelled'
    result plus spent fence, so a host that shows up later finds
    nothing to solve — and the withdrawal is counted and span-closed,
    not silently dropped."""
    from ccsc_code_iccv2017_tpu.serve.dqueue import DurableQueue

    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        metrics_dir=os.path.join(str(tmp_path), "m-fe"),
        verbose="none", poll_s=0.02,
    )
    try:
        b, m, x = _requests(1)[0]
        f = fe.submit(b, mask=m, x_orig=x, key="bail")
        assert f.cancel()
        t_end = time.time() + 10.0
        while fe.n_cancelled < 1 and time.time() < t_end:
            time.sleep(0.01)
        assert fe.n_cancelled == 1
        probe = DurableQueue(
            os.path.join(str(tmp_path), "q"), host="probe"
        )
        rec = probe.result("bail")
        assert rec is not None and rec["status"] == "cancelled"
        assert probe.spent("bail")
        # the late host's claim refuses the withdrawn item
        probe.join()
        assert probe.claim(limit=4) == []
    finally:
        fe.close()
    evs = obs.read_events(str(tmp_path), recursive=True)
    cc = [e for e in evs if e["type"] == "request_cancelled"]
    assert cc and cc[0].get("where") == "dqueue"
    root_ends = [
        e for e in evs
        if e["type"] == "span_end"
        and e.get("span") == trace_util.ROOT_SPAN
        and e.get("key") == "bail"
    ]
    assert [e.get("status") for e in root_ends] == ["cancelled"]


def test_cross_host_deadline_writes_durable_result(tmp_path):
    """An end-to-end budget stamped at the frontend is honoured by a
    host that arrives only AFTER expiry: the claim resolves the item
    as a durable 'deadline' result (never leasing a solve slot), and
    the frontend future raises the SAME DeadlineExceeded the
    in-process fleet would — where='claim', honesty over a hang."""
    from ccsc_code_iccv2017_tpu.serve import DeadlineExceeded
    from ccsc_code_iccv2017_tpu.serve.dqueue import DurableQueue

    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        metrics_dir=os.path.join(str(tmp_path), "m-fe"),
        verbose="none", poll_s=0.02,
    )
    try:
        b, m, x = _requests(1)[0]
        f = fe.submit(
            b, mask=m, x_orig=x, key="late", deadline_ms=50.0
        )
        time.sleep(0.15)  # budget lapses before any host exists
        ev = []
        host_q = DurableQueue(
            os.path.join(str(tmp_path), "q"), host="H0",
            emit=lambda t, **fi: ev.append(dict(fi, type=t)),
        )
        host_q.join()
        assert host_q.claim(limit=4) == []  # resolved, not leased
        rec = host_q.result("late")
        assert rec is not None and rec["status"] == "deadline"
        assert host_q.spent("late")
        with pytest.raises(DeadlineExceeded) as ei:
            f.result(timeout=30)
        assert ei.value.where == "claim"
        assert fe.n_failed == 1
        kinds = [
            (e["type"], e.get("where")) for e in ev
            if e["type"] == "deadline_exceeded"
        ]
        assert ("deadline_exceeded", "claim") in kinds
    finally:
        fe.close()
    evs = obs.read_events(str(tmp_path), recursive=True)
    root_ends = [
        e for e in evs
        if e["type"] == "span_end"
        and e.get("span") == trace_util.ROOT_SPAN
        and e.get("key") == "late"
    ]
    assert [e.get("status") for e in root_ends] == ["deadline"]


def test_cross_host_hedge_duplicates_suppressed(tmp_path, monkeypatch):
    """Hedging inside a federated host never double-delivers across
    the durable layer: with one replica injected slow-but-alive, the
    host's fleet hedges stuck attempts onto its healthy replica,
    exactly ONE durable result lands per key (the loser is suppressed
    by the same spent-key fence and counted hedge_lost), every
    frontend future resolves once, and the bytes are bit-identical to
    an unfaulted fleet's serve of the same stream."""
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from ccsc_code_iccv2017_tpu.serve.dqueue import DurableQueue

    d = _bank()
    geom, cfg, scfg = _cfgs()
    reqs = _requests(6)
    # reference BEFORE the fault env lands: an unfaulted plain fleet
    ref_fleet = ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(replicas=1, min_queue_depth=64, verbose="none"),
    )
    ref = [
        ref_fleet.reconstruct(b, mask=m, x_orig=x, timeout=180)
        for b, m, x in reqs
    ]
    ref_fleet.close()
    # replica 0 of the HOST fleet: sustained ~0.8 s/request — slow,
    # not hung, so the watchdog must stay silent
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_S", "0.8")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REPLICA", "0")
    host = FederatedHost(
        os.path.join(str(tmp_path), "q"), d,
        ReconstructionProblem(geom), cfg, scfg,
        FleetConfig(
            replicas=2, min_queue_depth=64, restart_backoff_s=0.05,
            hedge_after_ms=120.0, hedge_max_frac=1.0,
            health_interval_s=0.02, verbose="none",
        ),
        host="hostA", metrics_dir=os.path.join(str(tmp_path), "m-host"),
        heartbeat_s=0.2, ttl_s=1.0, skew_s=0.2, verbose="none",
    )
    fe = FederatedFrontend(
        os.path.join(str(tmp_path), "q"), client="fe0",
        metrics_dir=os.path.join(str(tmp_path), "m-fe"),
        verbose="none", poll_s=0.02,
    )
    try:
        futs = [
            fe.submit(b, mask=m, x_orig=x, key=f"k{i}")
            for i, (b, m, x) in enumerate(reqs)
        ]
        res = [f.result(timeout=180) for f in futs]
        fe.seal()
        assert host.serve_until_sealed(timeout=120)
    finally:
        host.close()
        fe.close()
    for i, (got, want) in enumerate(zip(res, ref)):
        assert np.array_equal(got.recon, want.recon), f"k{i}"
    # durable layer: exactly ONE result record per key, all ok
    probe = DurableQueue(os.path.join(str(tmp_path), "q"), host="probe")
    names = probe.result_names()
    assert len(names) == len(reqs)
    for i in range(len(reqs)):
        assert probe.result(f"k{i}")["status"] == "ok"
    evs = obs.read_events(str(tmp_path), recursive=True)
    by = {}
    for e in evs:
        by.setdefault(e["type"], []).append(e)
    spawns = by.get("hedge_spawn", [])
    wins = by.get("hedge_win", [])
    losses = by.get("hedge_lost", [])
    assert spawns, "the slow replica never provoked a hedge"
    assert len(wins) == len(losses)  # every win suppressed its loser
    assert len(spawns) <= len(reqs)  # cap: hedge_max_frac=1.0
    # slow is not dead: the watchdog must NOT have fired
    assert not by.get("stall", [])
    assert not by.get("fleet_replica_dead", [])
    # every trace reassembles complete across frontend + host streams
    traces = trace_util.assemble(evs)
    for r in res:
        assert traces[r.trace_id].complete
