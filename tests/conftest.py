"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; XLA's host-platform
device-count flag is the fake cluster (SURVEY.md section 4).

Note: this image's sitecustomize force-registers the experimental
'axon' TPU platform before conftest runs, so setting JAX_PLATFORMS in
the environment is not enough — we override via jax.config, which works
as long as no backend has been initialized yet.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 (ROADMAP.md) deselects these with -m 'not slow'; register
    # the marker so plain pytest doesn't warn about it
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (engine soak etc.) excluded from the "
        "tier-1 -m 'not slow' run",
    )
