"""Hardened input boundaries (utils.validate, hardened utils.io_mat).

Every public entry point — the three learners, reconstruct, the data
loaders, and the app CLIs — must reject malformed inputs with an
actionable CCSCInputError BEFORE anything is dispatched, instead of a
deferred XLA shape error or (worse) a silent NaN divergence. Plus the
lint asserting every app CLI actually routes its inputs through
utils.validate.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.io import savemat

from ccsc_code_iccv2017_tpu.config import (
    LearnConfig,
    ProblemGeom,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.utils import io_mat, validate
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError

GEOM = ProblemGeom((3, 3), 4)


def _data(n=4, side=12):
    return np.array(
        jax.random.normal(jax.random.PRNGKey(1), (n, side, side)),
        np.float32,
    )


# ------------------------------------------------------------- unit checks


def test_check_finite_rejects_nan_and_inf():
    with pytest.raises(CCSCInputError, match="non-finite"):
        validate.check_finite("data", np.array([1.0, np.nan]))
    with pytest.raises(CCSCInputError, match="non-finite"):
        validate.check_finite("data", np.array([np.inf, 1.0]))
    validate.check_finite("data", np.array([1.0, 2.0]))
    validate.check_finite("ints", np.array([1, 2]))  # trivially finite


def test_check_learn_data_geometry():
    # wrong rank: missing batch axis
    with pytest.raises(CCSCInputError, match="axes"):
        validate.check_learn_data(_data()[0], GEOM)
    # kernel larger than signal
    with pytest.raises(CCSCInputError, match="exceeds"):
        validate.check_learn_data(
            _data(side=8), ProblemGeom((11, 11), 4)
        )
    # block divisibility, with the historical message preserved
    with pytest.raises(CCSCInputError, match="not divisible"):
        validate.check_learn_data(_data(n=4), GEOM, num_blocks=3)
    # reduce mismatch
    with pytest.raises(CCSCInputError, match="reduce"):
        validate.check_learn_data(
            np.zeros((2, 3, 10, 10), np.float32),
            ProblemGeom((3, 3), 4, reduce_shape=(2,)),
        )
    validate.check_learn_data(_data(), GEOM, num_blocks=2)


def test_check_mask():
    b = _data()
    with pytest.raises(CCSCInputError, match="does not match data"):
        validate.check_mask(np.ones((4, 6, 6), np.float32), b)
    with pytest.raises(CCSCInputError, match="identically zero"):
        validate.check_mask(np.zeros_like(b), b)
    validate.check_mask(np.ones_like(b), b)


def test_check_filters():
    d = np.zeros((4, 3, 3), np.float32)
    validate.check_filters(d, GEOM)
    with pytest.raises(CCSCInputError, match="does not match"):
        validate.check_filters(np.zeros((5, 3, 3), np.float32), GEOM)
    with pytest.raises(CCSCInputError, match="non-finite"):
        validate.check_filters(np.full((4, 3, 3), np.nan), GEOM)


def test_check_config_positivity():
    with pytest.raises(CCSCInputError, match="rho_d"):
        validate.check_learn_config(LearnConfig(rho_d=0.0))
    with pytest.raises(CCSCInputError, match="lambda_prior"):
        validate.check_learn_config(LearnConfig(lambda_prior=-1.0))
    with pytest.raises(CCSCInputError, match="gamma_factor"):
        validate.check_solve_config(SolveConfig(gamma_factor=0.0))
    validate.check_learn_config(LearnConfig())
    validate.check_solve_config(SolveConfig())


# ------------------------------------------------- learner / solver entry


def test_learn_rejects_nan_data():
    from ccsc_code_iccv2017_tpu.models.learn import learn

    b = _data()
    b[1, 3, 3] = np.nan
    with pytest.raises(CCSCInputError, match="non-finite"):
        learn(jnp.asarray(b), GEOM, LearnConfig(num_blocks=2))


def test_learn_masked_rejects_bad_gamma_and_nan():
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked

    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    b = np.random.default_rng(0).uniform(
        0.1, 1.0, (2, 2, 10, 10)
    ).astype(np.float32)
    with pytest.raises(CCSCInputError, match="gamma_div_d"):
        learn_masked(jnp.asarray(b), geom, LearnConfig(), gamma_div_d=0.0)
    b[0, 0, 0, 0] = np.inf
    with pytest.raises(CCSCInputError, match="non-finite"):
        learn_masked(jnp.asarray(b), geom, LearnConfig())


def test_learn_masked_ignores_consensus_num_blocks():
    """The masked learner never consensus-splits the batch, so a
    consensus-tuned num_blocks that doesn't divide n must not reject
    its inputs."""
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked

    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    b = np.random.default_rng(0).uniform(
        0.1, 1.0, (2, 2, 10, 10)
    ).astype(np.float32)
    res = learn_masked(
        jnp.asarray(b), geom,
        LearnConfig(max_it=1, max_it_d=1, max_it_z=1, num_blocks=3,
                    verbose="none"),
        gamma_div_d=50.0, gamma_div_z=10.0, key=jax.random.PRNGKey(0),
    )
    assert np.isfinite(np.asarray(res.d)).all()


def test_learn_streaming_rejects_kernel_too_big():
    from ccsc_code_iccv2017_tpu.parallel.streaming import learn_streaming

    with pytest.raises(CCSCInputError, match="exceeds"):
        learn_streaming(
            _data(side=8), ProblemGeom((11, 11), 4), LearnConfig()
        )


def test_reconstruct_rejects_mask_mismatch():
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
        reconstruct,
    )

    b = _data(n=1)
    d = np.zeros((4, 3, 3), np.float32)
    d[:, 1, 1] = 1.0
    with pytest.raises(CCSCInputError, match="does not match data"):
        reconstruct(
            jnp.asarray(b),
            jnp.asarray(d),
            ReconstructionProblem(GEOM),
            SolveConfig(max_it=1),
            mask=jnp.ones((1, 6, 6), jnp.float32),
        )


# ----------------------------------------------------------- .mat loading


def test_corrupt_mat_raises_input_error(tmp_path):
    p = tmp_path / "bank.mat"
    p.write_bytes(b"MATLAB 5.0 MAT-file, truncated garbage")
    with pytest.raises(CCSCInputError, match="truncated|corrupt"):
        io_mat.load_filters_2d(str(p))
    with pytest.raises(CCSCInputError, match="no such"):
        io_mat.load_filters_2d(str(tmp_path / "missing.mat"))


def test_truncated_mat_raises_input_error(tmp_path):
    p = tmp_path / "bank.mat"
    savemat(p, {"d": np.zeros((3, 3, 4), np.float32)})
    blob = p.read_bytes()
    p.write_bytes(blob[: len(blob) // 3])  # tear the file
    with pytest.raises(CCSCInputError, match="truncated|corrupt"):
        io_mat.load_filters_2d(str(p))


def test_mat_missing_variable_raises_input_error(tmp_path):
    p = tmp_path / "bank.mat"
    savemat(p, {"not_d": np.zeros((3, 3, 4), np.float32)})
    with pytest.raises(CCSCInputError, match="no variable 'd'"):
        io_mat.load_filters_2d(str(p))


def test_mat_stack_with_nan_raises_input_error(tmp_path):
    from ccsc_code_iccv2017_tpu.data.images import load_images

    stack = np.moveaxis(_data(), 0, -1)
    stack[0, 0, 0] = np.nan
    p = tmp_path / "stack.mat"
    savemat(p, {"images": stack})
    with pytest.raises(CCSCInputError, match="non-finite"):
        load_images(str(p))


# ------------------------------------------------------------ CLI surface


def _mat_stack(tmp_path, n=4, side=12, nan_at=None):
    b = _data(n=n, side=side)
    if nan_at is not None:
        b[nan_at] = np.nan
    p = tmp_path / "stack.mat"
    savemat(p, {"b": b})  # framework layout [n, H, W]
    return str(p)


def test_learn_2d_cli_nan_data(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import learn_2d

    data = _mat_stack(tmp_path, nan_at=(1, 2, 2))
    with pytest.raises(CCSCInputError, match="non-finite"):
        learn_2d.main(
            ["--data", data, "--filters", "4", "--support", "3",
             "--blocks", "2", "--contrast", "none", "--max-it", "1"]
        )


def test_learn_2d_cli_kernel_exceeds_signal(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import learn_2d

    data = _mat_stack(tmp_path)
    with pytest.raises(CCSCInputError, match="exceeds"):
        learn_2d.main(
            ["--data", data, "--filters", "4", "--support", "21",
             "--blocks", "2", "--contrast", "none", "--max-it", "1"]
        )


def test_learn_2d_cli_bad_blocks(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import learn_2d

    data = _mat_stack(tmp_path)
    with pytest.raises(CCSCInputError, match="not divisible"):
        learn_2d.main(
            ["--data", data, "--filters", "4", "--support", "3",
             "--blocks", "3", "--contrast", "none", "--max-it", "1"]
        )


def test_learn_2d_cli_corrupt_mat(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import learn_2d

    p = tmp_path / "stack.mat"
    p.write_bytes(b"not a mat file at all")
    with pytest.raises(CCSCInputError, match="truncated|corrupt"):
        learn_2d.main(
            ["--data", str(p), "--filters", "4", "--support", "3",
             "--blocks", "2", "--contrast", "none", "--max-it", "1"]
        )


def test_learn_3d_cli_kernel_exceeds_signal():
    from ccsc_code_iccv2017_tpu.apps import learn_3d

    with pytest.raises(CCSCInputError, match="exceeds"):
        learn_3d.main(
            ["--synthetic", "--clips", "4", "--clip-size", "8",
             "--support", "11", "--support-t", "11", "--filters", "4",
             "--blocks", "2", "--max-it", "1"]
        )


def test_learn_hyperspectral_cli_nan_mat(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import learn_hyperspectral

    cube = np.random.default_rng(0).uniform(
        0.1, 1.0, (10, 10, 4, 2)
    ).astype(np.float32)  # [x y w n]
    cube[0, 0, 0, 0] = np.nan
    p = tmp_path / "cubes.mat"
    savemat(p, {"b": cube})
    with pytest.raises(CCSCInputError, match="non-finite"):
        learn_hyperspectral.main(
            ["--mat", str(p), "--filters", "4", "--support", "3",
             "--max-it", "1"]
        )


def test_inpaint_cli_corrupt_filters(tmp_path):
    from ccsc_code_iccv2017_tpu.apps import inpaint_2d

    bank = tmp_path / "bank.mat"
    bank.write_bytes(b"garbage that is not a mat file")
    data = _mat_stack(tmp_path)
    with pytest.raises(CCSCInputError, match="truncated|corrupt"):
        inpaint_2d.main(
            ["--data", data, "--filters", str(bank), "--max-it", "1"]
        )


# ------------------------------------------------------------------- lint


APPS_DIR = os.path.join(
    os.path.dirname(__file__), "..", "ccsc_code_iccv2017_tpu", "apps"
)
def test_every_app_cli_routes_inputs_through_validate():
    """Thin wrapper over the migrated `validate-routing` analysis
    check (ccsc_code_iccv2017_tpu/analysis/conventions.py): every app
    CLI must import utils.validate and call at least one of its
    check_* functions before dispatch — a new app that skips the
    input boundary fails CI, not a user's run. The full suite runs in
    tests/test_analysis.py."""
    from ccsc_code_iccv2017_tpu.analysis import core

    pkg_root = os.path.normpath(os.path.join(APPS_DIR, ".."))
    project = core.Project(
        [pkg_root], repo_root=os.path.dirname(pkg_root)
    )
    offenders = core.run_checks(project, ["validate-routing"])
    assert not offenders, (
        "app CLIs must route their inputs through utils.validate "
        "before dispatching:\n"
        + "\n".join(f.render() for f in offenders)
    )
