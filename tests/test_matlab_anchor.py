"""MATLAB-anchored golden trajectory (VERDICT r1 missing #8).

Everything else in tests/ checks the implementation against oracles
written from the same reading of the math. This file breaks that loop:
it is a LITERAL, line-ordered float64 transcription of the reference
inpainting solver 2D/Inpainting/admm_solve_conv2D_weighted_sampling.m
— full complex fft2, psf2otf, the exact MATLAB update order and
gamma heuristic, transcribed statement by statement (citations inline)
rather than re-derived. If the framework and this transcription agree
on a trajectory, a shared systematic misreading would have to survive
two independent renderings of the MATLAB text.

The transcription exists only as a test fixture; the framework's
solver (models.reconstruct) shares no code or structure with it
(rfft + einsum Woodbury vs flattened repmat Sherman-Morrison).
"""
import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ccsc_code_iccv2017_tpu.config import ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)


def psf2otf(k, shape):
    """MATLAB psf2otf: zero-pad to shape, circshift the center to the
    origin, fft2 (used at admm_solve_conv2D_weighted_sampling.m:161)."""
    p = np.zeros(shape, np.float64)
    p[: k.shape[0], : k.shape[1]] = k
    p = np.roll(p, (-(k.shape[0] // 2), -(k.shape[1] // 2)), (0, 1))
    return np.fft.fft2(p)


def matlab_inpainting_solver(b, kmat, mask, lam_res, lam_pri, max_it):
    """Statement-for-statement transcription of
    admm_solve_conv2D_weighted_sampling.m (lines cited per step).
    smooth_init = 0, verbose trajectory returned instead of printed.
    Returns (obj_vals[0..max_it], res)."""
    # :10-11 psf_radius, padded size
    r = (kmat.shape[0] // 2, kmat.shape[1] // 2)
    size_x = (b.shape[0] + 2 * r[0], b.shape[1] + 2 * r[1])
    K = kmat.shape[2]
    # :12 precompute_H_hat (:155-168)
    dhat = np.stack(
        [psf2otf(kmat[:, :, i], size_x) for i in range(K)], axis=2
    )
    dhatTdhat = np.sum(np.conj(dhat) * dhat, axis=2)  # :166
    # :28 precompute_MProx (:146-153), smoothinit = 0
    M = np.zeros(size_x)
    M[r[0] : r[0] + b.shape[0], r[1] : r[1] + b.shape[1]] = mask
    MtM = M * M  # :151
    Mtb = np.zeros(size_x)
    Mtb[r[0] : r[0] + b.shape[0], r[1] : r[1] + b.shape[1]] = b * mask  # :152
    # :35-37 lambdas and gammas
    lam = (lam_res, lam_pri)
    gamma_h = 60.0 * lam_pri / np.max(b)
    gamma = (gamma_h / 100.0, gamma_h)
    rho = gamma[1] / gamma[0]  # solve_conv_term :178

    def prox_data_masked(u, theta):  # :29
        return (Mtb + u / theta) / (MtM + 1.0 / theta)

    def prox_sparse(u, theta):  # :32
        with np.errstate(divide="ignore", invalid="ignore"):
            f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
        return np.maximum(0.0, f) * u

    def objective(z):  # :192-202
        Dz = np.real(
            np.fft.ifft2(
                np.sum(dhat * np.fft.fft2(z, axes=(0, 1)), axis=2)
            )
        )
        crop = Dz[r[0] : size_x[0] - r[0], r[1] : size_x[1] - r[1]]
        f_z = lam_res * 0.5 * np.sum((mask * crop - mask * b) ** 2)
        return f_z + lam_pri * np.sum(np.abs(z))

    def solve_conv_term(xi1h, xi2h):  # :170-190
        # b_k = conj(dhat_k) xi1 + rho xi2_k   (:181)
        bvec = np.conj(dhat) * xi1h[:, :, None] + rho * xi2h
        # scalar Sherman-Morrison inverse (:184-185)
        sc = 1.0 / (rho + dhatTdhat)
        corr = np.sum(dhat * bvec, axis=2)  # sum_j conj(dhatT)*b (:185)
        return bvec / rho - (sc * corr)[:, :, None] * np.conj(dhat) / rho

    # :42-51 zero init
    size_z = (size_x[0], size_x[1], K)
    d1 = np.zeros(size_x)
    d2 = np.zeros(size_z)
    z = np.zeros(size_z)
    z_hat = np.zeros(size_z, complex)
    obj_vals = [objective(z)]  # :68 (iter 0 print)

    for _ in range(max_it):  # :81
        v1 = np.real(np.fft.ifft2(np.sum(dhat * z_hat, axis=2)))  # :84
        v2 = z  # :85
        u1 = prox_data_masked(v1 - d1, lam[0] / gamma[0])  # :88
        u2 = prox_sparse(v2 - d2, lam[1] / gamma[1])  # :89
        d1 = d1 - (v1 - u1)  # :93
        d2 = d2 - (v2 - u2)
        xi1_hat = np.fft.fft2(u1 + d1)  # :96-97
        xi2_hat = np.fft.fft2(u2 + d2, axes=(0, 1))
        z_hat = solve_conv_term(xi1_hat, xi2_hat)  # :103
        z = np.real(np.fft.ifft2(z_hat, axes=(0, 1)))  # :104
        obj_vals.append(objective(z))  # :123
    Dz = np.real(np.fft.ifft2(np.sum(dhat * z_hat, axis=2)))  # :141
    res = Dz[r[0] : size_x[0] - r[0], r[1] : size_x[1] - r[1]]  # :142
    return np.array(obj_vals), res


def test_framework_matches_matlab_transcription():
    rng = np.random.default_rng(42)
    b = rng.uniform(0.1, 1.0, (12, 12))
    mask = (rng.uniform(size=(12, 12)) > 0.5).astype(np.float64)
    kmat = rng.normal(size=(3, 3, 4))
    kmat /= np.sqrt(np.sum(kmat**2, axis=(0, 1), keepdims=True))

    max_it = 4
    obj_ml, res_ml = matlab_inpainting_solver(
        b, kmat, mask, lam_res=5.0, lam_pri=2.0, max_it=max_it
    )

    geom = ProblemGeom((3, 3), 4)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(
        lambda_residual=5.0,
        lambda_prior=2.0,
        max_it=max_it,
        tol=0.0,
        gamma_factor=60.0,
        gamma_ratio=100.0,
        verbose="none",
        track_objective=True,
    )
    d = np.moveaxis(kmat, -1, 0)  # [k, s, s] framework layout
    res = reconstruct(
        jnp.asarray(b[None].astype(np.float32)),
        jnp.asarray(d.astype(np.float32)),
        prob,
        cfg,
        mask=jnp.asarray(mask[None].astype(np.float32)),
    )
    obj_fw = np.asarray(res.trace.obj_vals)[: max_it + 1]
    assert obj_ml[0] == pytest.approx(obj_fw[0], rel=1e-4)
    np.testing.assert_allclose(obj_fw, obj_ml, rtol=5e-4)
    np.testing.assert_allclose(
        np.asarray(res.recon)[0], res_ml, atol=5e-4
    )
    # pin the anchored trajectory as literals so drift in EITHER
    # rendering (transcription or framework) trips the test
    expected = np.array(
        [56.18919067, 54.14431462, 55.35870044, 54.59166188, 53.21755806]
    )
    np.testing.assert_allclose(obj_ml, expected, rtol=1e-7)
    assert float(np.sum(res_ml)) == pytest.approx(2.2126866250765, rel=1e-9)
