"""Color conversion and frame-selection paths of the image loader
(CreateImages.m:100-107 frame striding, :253-281 color dispatch)."""
import os

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.data import images as I

REF = "/root/reference"
needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference not mounted"
)


def _rgb(seed=0, h=20, w=24):
    r = np.random.default_rng(seed)
    return (r.random((h, w, 3)) * 255).astype(np.uint8)


def test_ycbcr_matches_matlab_constants():
    # pure colors against MATLAB rgb2ycbcr([1 0 0; 0 1 0; 0 0 1; 1 1 1])
    rgb = np.array(
        [[[1, 0, 0], [0, 1, 0], [0, 0, 1], [1, 1, 1]]], np.float32
    )
    out = I.rgb_to_ycbcr(rgb) * 255.0
    expect = np.array(
        [
            [81.481, 90.203, 240.0],
            [144.553, 53.797, 34.214],
            [40.966, 240.0, 109.786],
            [235.0, 128.0, 128.0],
        ],
        np.float32,
    )
    np.testing.assert_allclose(out[0], expect, atol=1e-2)


def test_hsv_matches_colorsys():
    import colorsys

    rgb = _rgb(1).astype(np.float32) / 255.0
    out = I.rgb_to_hsv(rgb)
    for y in range(0, 20, 7):
        for x in range(0, 24, 9):
            h, s, v = colorsys.rgb_to_hsv(*rgb[y, x])
            np.testing.assert_allclose(
                out[y, x], [h, s, v], atol=1e-6, err_msg=f"{y},{x}"
            )


def test_convert_color_shapes_and_gray_equiv():
    img = _rgb(2)
    assert I.convert_color(img, "gray").shape == (20, 24)
    for mode in ("rgb", "ycbcr", "hsv"):
        out = I.convert_color(img, mode)
        assert out.shape == (20, 24, 3) and out.dtype == np.float32
    np.testing.assert_allclose(
        I.convert_color(img, "rgb") @ [0.2989, 0.5870, 0.1140],
        I.convert_color(img, "gray"),
        atol=1e-5,
    )


@needs_ref
def test_per_channel_local_cn_color_load():
    b = I.load_images(
        f"{REF}/2D/Inpainting/Test",
        contrast_normalize="local_cn",
        color="rgb",
        limit=2,
        size=(32, 32),
    )
    assert b.shape == (2, 32, 32, 3)
    assert np.isfinite(b).all()
    # per-channel CN: each channel separately normalized, so channel
    # means are near zero independently
    assert abs(b[..., 0].mean()) < 0.2 and abs(b[..., 2].mean()) < 0.2


def test_select_frames_matlab_semantics():
    items = list("abcdefghij")
    # MATLAB 1:2:7 -> indices 1,3,5,7 (1-based)
    assert I.select_frames(items, (1, 2, 7)) == ["a", "c", "e", "g"]
    # 'end' sentinel
    assert I.select_frames(items, (8, 1, "end")) == ["h", "i", "j"]
    # stop beyond length clamps
    assert I.select_frames(items, (9, 1, 99)) == ["i", "j"]
    assert I.select_frames(items, None) == items
    # negative strides are inclusive of the stop, like MATLAB 7:-2:1
    assert I.select_frames(items, (7, -2, 1)) == ["g", "e", "c", "a"]
    assert I.select_frames(items, ("end", -3, 1)) == ["j", "g", "d", "a"]
    # start beyond length clamps for descending strides
    assert I.select_frames(items, (99, -4, 1)) == ["j", "f", "b"]
    with pytest.raises(ValueError):
        I.select_frames(items, (1, 0, 5))


def test_gray_alpha_and_uint16_inputs():
    r = np.random.default_rng(5)
    la = (r.random((6, 7, 2)) * 255).astype(np.uint8)  # gray + alpha
    assert I.convert_color(la, "rgb").shape == (6, 7, 3)
    assert I.convert_color(la, "hsv").shape == (6, 7, 3)
    assert I.convert_color(la, "gray").shape == (6, 7)
    np.testing.assert_allclose(
        I.convert_color(la, "gray"), la[..., 0] / 255.0, atol=1e-6
    )
    u16 = (r.random((6, 7, 3)) * 65535).astype(np.uint16)
    rgb = I.convert_color(u16, "rgb")
    assert rgb.max() <= 1.0 and rgb.min() >= 0.0
    assert I.convert_color(u16, "gray").max() <= 1.0


def test_color_layouts():
    stack = np.arange(2 * 4 * 5 * 3, dtype=np.float32).reshape(2, 4, 5, 3)
    red = I.channels_to_reduce(stack)
    assert red.shape == (2, 3, 4, 5)
    np.testing.assert_array_equal(red[1, 2], stack[1, :, :, 2])
    bat = I.channels_to_batch(stack)
    assert bat.shape == (6, 4, 5)
    np.testing.assert_array_equal(bat[5], stack[1, :, :, 2])
    # gray stacks: 'reduce' inserts the singleton axis, 'batch' is id
    gray = np.zeros((2, 4, 5), np.float32)
    assert I._apply_layout(gray, "reduce").shape == (2, 1, 4, 5)
    assert I._apply_layout(gray, "batch").shape == (2, 4, 5)
    with pytest.raises(ValueError):
        I._apply_layout(gray, "nope")


@needs_ref
def test_native_loader_color_layout_matches_numpy():
    kw = dict(color="rgb", limit=2, size=(24, 24), layout="reduce")
    a = I.load_images(
        f"{REF}/2D/Inpainting/Test", contrast_normalize="local_cn", **kw
    )
    b = I.load_images_native(
        f"{REF}/2D/Inpainting/Test", contrast_normalize="local_cn", **kw
    )
    assert a.shape == b.shape == (2, 3, 24, 24)
    np.testing.assert_allclose(a, b, atol=2e-5)


@needs_ref
def test_frames_in_loader():
    all_f = I.load_image_list(f"{REF}/2D/Inpainting/Test")
    some = I.load_image_list(f"{REF}/2D/Inpainting/Test", frames=(1, 3, "end"))
    assert len(some) == len(all_f[::3])
    np.testing.assert_array_equal(some[1], all_f[3])


@needs_ref
def test_color_stack_whitening_per_channel():
    b = I.load_images(
        f"{REF}/2D/Inpainting/Test",
        contrast_normalize="PCA_whitening",
        color="rgb",
        limit=4,
        size=(24, 24),
    )
    assert b.shape == (4, 24, 24, 3) and np.isfinite(b).all()
