"""The reconstruction serving engine (serve.CodecEngine): per-bank
plans, shape-bucketed AOT warmup, micro-batched solves.

Contracts under test (ISSUE 5):
- a served result at an exact bucket shape is BIT-IDENTICAL to a
  direct reconstruct() call (each slot is an n=1 solve under vmap:
  per-request gamma, traces, and tol termination);
- a padded-bucket result equals the exact-shape solve on the valid
  region to boundary tolerance (the zero-mask pad path);
- second-and-later same-bucket requests trigger ZERO XLA compiles
  (asserted from the obs event stream);
- the micro-batch queue flushes on both max_batch (bucket slots) and
  max_wait_ms;
- per-request validation is the cheap subset (shape/non-finite), the
  bank checks having run once at engine construction.
"""
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)
from ccsc_code_iccv2017_tpu.serve import CodecEngine
from ccsc_code_iccv2017_tpu.utils import obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError


def _bank(k=6, s=5, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=8, tol=1e-4,
        verbose="none", track_objective=True, track_psnr=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _req(size, seed=1, keep=0.5):
    r = np.random.default_rng(seed)
    x = r.random((size, size)).astype(np.float32)
    m = (r.random((size, size)) < keep).astype(np.float32)
    return x, m


def _engine(d, cfg, buckets, tmp_path=None, **kw):
    scfg = ServeConfig(
        buckets=buckets,
        max_wait_ms=kw.pop("max_wait_ms", 10.0),
        metrics_dir=str(tmp_path) if tmp_path is not None else None,
        verbose="none",
        **kw,
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)


def test_exact_bucket_bit_identical_to_direct_call():
    """A request AT a bucket shape: served result == a standalone
    reconstruct() call, bitwise — recon, codes trace values, and the
    stopping iteration."""
    d = _bank()
    cfg = _cfg()
    eng = _engine(d, cfg, ((2, (24, 24)),))
    try:
        x, m = _req(24)
        res = eng.reconstruct(x * m, mask=m, x_orig=x)
        geom = ProblemGeom(d.shape[1:], d.shape[0])
        direct = reconstruct(
            jnp.asarray((x * m)[None]), d, ReconstructionProblem(geom),
            cfg, mask=jnp.asarray(m[None]), x_orig=jnp.asarray(x[None]),
        )
        np.testing.assert_array_equal(
            res.recon, np.asarray(direct.recon[0])
        )
        np.testing.assert_array_equal(
            np.asarray(res.trace.obj_vals),
            np.asarray(direct.trace.obj_vals),
        )
        np.testing.assert_array_equal(
            np.asarray(res.trace.psnr_vals),
            np.asarray(direct.trace.psnr_vals),
        )
        assert int(res.trace.num_iters) == int(direct.trace.num_iters)
        # .psnr is recomputed host-side over the valid region; at an
        # exact bucket shape that is the same region as the in-solve
        # trace, up to f32-vs-f64 reduction
        assert res.psnr == pytest.approx(
            float(direct.trace.psnr_vals[int(direct.trace.num_iters)]),
            abs=1e-3,
        )
    finally:
        eng.close()


def test_padded_bucket_matches_exact_shape_on_valid_region():
    """A request SMALLER than its bucket: the pad region is excluded
    through the mask path, so the valid-region result matches the
    exact-shape solve to boundary tolerance (same class as the
    fft_pad canvas-growth bound in test_reconstruct)."""
    d = _bank()
    cfg = _cfg(max_it=20)
    eng = _engine(d, cfg, ((2, (32, 32)),))
    try:
        x, m = _req(26, seed=3)
        res = eng.reconstruct(x * m, mask=m)
        assert res.bucket == "2@32x32"
        assert res.recon.shape == (26, 26)
        geom = ProblemGeom(d.shape[1:], d.shape[0])
        direct = reconstruct(
            jnp.asarray((x * m)[None]), d, ReconstructionProblem(geom),
            cfg, mask=jnp.asarray(m[None]),
        )
        ref = np.asarray(direct.recon[0])
        rel = np.abs(res.recon - ref).max() / max(
            np.abs(ref).max(), 1e-9
        )
        assert rel < 0.05, rel
    finally:
        eng.close()


def test_second_request_zero_xla_compiles(tmp_path):
    """The zero-recompile serving contract, asserted from the obs
    event stream: every backend compile lands during engine warmup;
    requests — including the FIRST — dispatch with none."""
    d = _bank()
    eng = _engine(d, _cfg(), ((2, (24, 24)),), tmp_path=tmp_path)
    try:
        t_ready = time.time()
        x, m = _req(24)
        eng.reconstruct(x * m, mask=m)
        eng.reconstruct(x * m, mask=m)
        x2, m2 = _req(20, seed=5)  # padded into the same bucket
        eng.reconstruct(x2 * m2, mask=m2)
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    compiles = [e for e in events if e.get("type") == "compile"]
    assert compiles, "warmup must have recorded compile events"
    after = [e for e in compiles if e["t"] > t_ready]
    assert after == [], (
        f"requests triggered {len(after)} XLA compile(s): "
        f"{[e.get('fun_name') for e in after]}"
    )
    # and the summary's recompile tracker agrees: nothing compiled twice
    summary = next(
        e for e in reversed(events) if e.get("type") == "summary"
    )
    assert summary["compile"]["recompiled_funs"] == []


def test_queue_flushes_at_max_batch(tmp_path):
    """Filling a bucket's slots dispatches immediately (no wait for
    the deadline): one dispatch, occupancy 1.0."""
    d = _bank()
    eng = _engine(
        d, _cfg(), ((2, (24, 24)),), tmp_path=tmp_path,
        max_wait_ms=10_000.0,  # deadline can never be the trigger
    )
    try:
        x, m = _req(24)
        t0 = time.perf_counter()
        f1 = eng.submit(x * m, mask=m)
        f2 = eng.submit(x * m, mask=m)
        f1.result(timeout=60)
        f2.result(timeout=60)
        assert time.perf_counter() - t0 < 10.0  # did not sit out 10 s
    finally:
        eng.close()
    disp = [
        e
        for e in obs.read_events(str(tmp_path))
        if e.get("type") == "serve_dispatch"
    ]
    assert [e["n"] for e in disp] == [2]
    assert disp[0]["occupancy"] == 1.0


def test_queue_flushes_at_max_wait(tmp_path):
    """A lone request dispatches after max_wait_ms even though its
    bucket never fills."""
    d = _bank()
    wait_ms = 150.0
    eng = _engine(
        d, _cfg(), ((4, (24, 24)),), tmp_path=tmp_path,
        max_wait_ms=wait_ms,
    )
    try:
        x, m = _req(24)
        fut = eng.submit(x * m, mask=m)
        res = fut.result(timeout=60)
        # it waited (roughly) the deadline, not forever and not zero
        assert res.wait_s >= 0.8 * wait_ms / 1e3
    finally:
        eng.close()
    disp = [
        e
        for e in obs.read_events(str(tmp_path))
        if e.get("type") == "serve_dispatch"
    ]
    assert [e["n"] for e in disp] == [1]
    assert disp[0]["slots"] == 4


def test_full_bucket_stream_does_not_starve_deadline(tmp_path):
    """A steady stream keeping one bucket full must not starve another
    bucket's lone request past its max_wait deadline: expired
    deadlines flush before full buckets."""
    d = _bank()
    wait_ms = 100.0
    eng = _engine(
        d, _cfg(max_it=4), ((1, (20, 20)), (4, (32, 32))),
        tmp_path=tmp_path, max_wait_ms=wait_ms,
    )
    try:
        xs, ms = _req(20)
        xb, mb = _req(30, seed=9)
        lone = eng.submit(xb * mb, mask=mb)  # 32-bucket, never fills
        # saturate the 1-slot small bucket: every submit makes it full
        small = [eng.submit(xs * ms, mask=ms) for _ in range(8)]
        res = lone.result(timeout=60)
        # it must have been served close to its deadline, not behind
        # the whole small-bucket stream
        assert res.wait_s < 8 * wait_ms / 1e3, res.wait_s
        for f in small:
            f.result(timeout=60)
    finally:
        eng.close()


def test_psnr_none_when_tracking_off():
    """x_orig given but the pinned config does not track PSNR: the
    result must say None, never a fake 0.0 dB."""
    d = _bank()
    cfg = _cfg(track_psnr=False)
    eng = _engine(d, cfg, ((2, (24, 24)),))
    try:
        x, m = _req(24)
        res = eng.reconstruct(x * m, mask=m, x_orig=x)
        assert res.psnr is None
        assert float(np.abs(np.asarray(res.trace.psnr_vals)).max()) == 0.0
    finally:
        eng.close()


def test_cancelled_future_does_not_poison_batch(tmp_path):
    """A client-cancelled pending request is dropped at dispatch; its
    batch siblings still get their results."""
    d = _bank()
    eng = _engine(
        d, _cfg(max_it=4), ((2, (24, 24)),), tmp_path=tmp_path,
        max_wait_ms=300.0,
    )
    try:
        x, m = _req(24)
        f1 = eng.submit(x * m, mask=m)
        assert f1.cancel()  # still queued: cancellable
        f2 = eng.submit(x * m, mask=m)
        f3 = eng.submit(x * m, mask=m)  # fills the 2-slot bucket
        assert f2.result(timeout=60).recon.shape == (24, 24)
        assert f3.result(timeout=60).recon.shape == (24, 24)
        assert f1.cancelled()
    finally:
        eng.close()


def test_bucket_selection_and_oversize_refusal():
    d = _bank()
    eng = _engine(d, _cfg(), ((2, (24, 24)), (2, (40, 40))))
    try:
        assert eng.bucket_for((20, 24)) == (2, (24, 24))
        assert eng.bucket_for((25, 10)) == (2, (40, 40))
        with pytest.raises(CCSCInputError, match="exceeds every"):
            eng.bucket_for((64, 64))
        # submit() routes through the same refusal
        x, m = _req(64)
        with pytest.raises(CCSCInputError, match="exceeds every"):
            eng.submit(x * m, mask=m)
    finally:
        eng.close()


def test_per_request_validation_is_the_cheap_subset():
    """Bad per-request data fails fast with the named check; the bank
    itself was validated once at construction (a bad bank never
    constructs an engine)."""
    d = _bank()
    eng = _engine(d, _cfg(), ((2, (24, 24)),))
    try:
        x, m = _req(24)
        bad = x.copy()
        bad[3, 3] = np.nan
        with pytest.raises(CCSCInputError, match="non-finite"):
            eng.submit(bad)
        with pytest.raises(CCSCInputError, match="no batch axis"):
            eng.submit(x[None])
        with pytest.raises(CCSCInputError, match="mask shape"):
            eng.submit(x, mask=m[:12])
        # same all-zero-mask refusal as the direct reconstruct() path
        with pytest.raises(CCSCInputError, match="identically zero"):
            eng.submit(x, mask=np.zeros_like(m))
    finally:
        eng.close()
    # construction-time (hoisted) check: NaN bank refused before any
    # compile
    bad_bank = np.asarray(_bank()).copy()
    bad_bank[0, 0, 0] = np.inf
    with pytest.raises(CCSCInputError, match="non-finite"):
        _engine(jnp.asarray(bad_bank), _cfg(), ((2, (24, 24)),))


def test_requests_without_optional_fields_match_direct_none_path():
    """mask=None / smooth_init=None / x_orig=None requests run the
    same math as the direct call's None path (the engine feeds
    neutral fills: ones mask, zero offset)."""
    d = _bank()
    cfg = _cfg()
    eng = _engine(d, cfg, ((2, (24, 24)),))
    try:
        x, _ = _req(24, seed=7)
        res = eng.reconstruct(x)  # fully observed, no extras
        geom = ProblemGeom(d.shape[1:], d.shape[0])
        direct = reconstruct(
            jnp.asarray(x[None]), d, ReconstructionProblem(geom), cfg
        )
        np.testing.assert_array_equal(
            res.recon, np.asarray(direct.recon[0])
        )
        assert res.psnr is None
        assert float(np.abs(np.asarray(res.trace.psnr_vals)).max()) == 0.0
    finally:
        eng.close()


def test_close_idempotent_reentrant_and_closed_property():
    """ISSUE 7 satellite: close() must be re-entrant and race-safe (a
    fleet drain racing a user close), with a ``closed`` property the
    fleet can poll. The second close returns AFTER the first finished
    the drain, and a closed engine refuses new work."""
    import threading

    d = _bank()
    eng = _engine(d, _cfg(max_it=4), ((2, (24, 24)),))
    assert eng.closed is False
    x, m = _req(24)
    fut = eng.submit(x * m, mask=m)
    done = []
    threads = [
        threading.Thread(target=lambda: (eng.close(), done.append(1)))
        for _ in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert done == [1, 1, 1]  # every closer returned
    assert eng.closed is True
    # the pre-close request was flushed, not dropped
    assert fut.result(timeout=5).recon.shape == (24, 24)
    eng.close()  # idempotent after the fact too
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(x * m, mask=m)


def test_close_noop_when_constructor_failed_early():
    """The documented close() contract holds from the FIRST statement
    of __init__: a constructor that raised in the pre-telemetry
    validation block (before _run/_cv exist) must still close as a
    clean no-op, not mask the validation error with AttributeError."""
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve.engine import CodecEngine
    from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError

    d = _bank()
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    eng = CodecEngine.__new__(CodecEngine)
    with pytest.raises(CCSCInputError, match="smaller than the"):
        # bucket smaller than the kernel support: raises in the
        # once-per-engine validation, before obs.start_run
        eng.__init__(
            d, ReconstructionProblem(geom), _cfg(),
            ServeConfig(buckets=((2, (4, 4)),), verbose="none"),
        )
    eng.close()  # the caller's `finally: engine.close()`
    eng.close()  # and it stays idempotent


def test_drain_pending_hands_off_queued_requests():
    """The fleet handoff hook: queued (not yet dispatching) requests
    are atomically removed with their payloads, their engine futures
    cancelled — the caller requeues them elsewhere."""
    d = _bank()
    eng = _engine(
        d, _cfg(), ((2, (24, 24)),), max_wait_ms=60_000.0,
    )
    try:
        x, m = _req(24)
        fut = eng.submit(x * m, mask=m)  # 1 of 2 slots: waits out the
        # deadline, so it is still queued when we drain
        taken = eng.drain_pending()
        assert len(taken) == 1
        assert fut.cancelled()
        np.testing.assert_array_equal(taken[0]["b"], x * m)
        np.testing.assert_array_equal(taken[0]["mask"], m)
        assert eng.drain_pending() == []  # empty after the handoff
    finally:
        eng.close()


def test_set_max_wait_ms_live_retarget():
    """Overload rung 1: zeroing the flush deadline live dispatches a
    lone queued request immediately instead of waiting out the
    configured deadline."""
    d = _bank()
    eng = _engine(
        d, _cfg(max_it=4), ((2, (24, 24)),), max_wait_ms=60_000.0,
    )
    try:
        x, m = _req(24)
        t0 = time.perf_counter()
        fut = eng.submit(x * m, mask=m)
        eng.set_max_wait_ms(0.0)
        res = fut.result(timeout=60)
        assert time.perf_counter() - t0 < 30.0  # not the 60 s deadline
        assert res.recon.shape == (24, 24)
    finally:
        eng.close()


def test_serving_bound_formula():
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    b = perfmodel.serving_bound(
        300.0, iters_per_request=30.0, slots=4, occupancy=0.5
    )
    assert b["requests_per_sec"] == pytest.approx(300.0 * 4 * 0.5 / 30.0)
    assert perfmodel.serving_bound(300.0, 0, 4)["requests_per_sec"] == 0.0


@pytest.mark.slow
def test_engine_soak_mixed_stream(tmp_path):
    """Soak: a mixed-size stream across two buckets, every result
    spot-checked against the direct exact-shape call on the valid
    region; the stream ends with zero compiles after warmup and a
    clean latency summary."""
    d = _bank(k=8)
    cfg = _cfg(max_it=12)
    eng = _engine(
        d, cfg, ((3, (24, 24)), (3, (32, 32))), tmp_path=tmp_path,
        max_wait_ms=5.0,
    )
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    r = np.random.default_rng(0)
    try:
        t_ready = time.time()
        reqs, futs = [], []
        for i in range(24):
            size = int(r.integers(18, 33))
            x, m = _req(size, seed=100 + i)
            reqs.append((x, m))
            futs.append(eng.submit(x * m, mask=m, x_orig=x))
        results = [f.result(timeout=300) for f in futs]
    finally:
        eng.close()
    # reference spot-checks AFTER close: the engine's compile monitor
    # is process-global while its run is open, and these direct calls
    # legitimately compile per shape — they must not count against the
    # engine's zero-recompile assertion below
    for i in (0, 7, 15, 23):
        x, m = reqs[i]
        direct = reconstruct(
            jnp.asarray((x * m)[None]), d,
            ReconstructionProblem(geom), cfg,
            mask=jnp.asarray(m[None]), x_orig=jnp.asarray(x[None]),
        )
        ref = np.asarray(direct.recon[0])
        rel = np.abs(results[i].recon - ref).max() / max(
            np.abs(ref).max(), 1e-9
        )
        assert rel < 0.06, (i, rel)
    events = obs.read_events(str(tmp_path))
    after = [
        e for e in events
        if e.get("type") == "compile" and e["t"] > t_ready
    ]
    assert after == []
    summary = next(
        e for e in reversed(events) if e.get("type") == "summary"
    )
    assert summary["n_requests"] == 24
    assert summary["p99_latency_s"] is not None
    st = eng.stats()
    assert st["n_requests"] == 24
    assert 0 < st["mean_occupancy"] <= 1.0


# --------------------------------------------------------------------
# staged warmup + compile-cache latch (ISSUE 16)
# --------------------------------------------------------------------


def test_staged_warmup_serves_hot_bucket_while_cold_builds(tmp_path):
    """Staged engine contract: the constructor returns as soon as the
    DECLARED-hot bucket's program is ready; a request for the still-
    cold bucket is refused with BucketCold (retry-after, not an
    error), the hot bucket serves immediately, and the cold bucket
    warms in the background and then serves."""
    from ccsc_code_iccv2017_tpu.serve import BucketCold

    d = _bank(k=4, s=3)
    cfg = _cfg(max_it=3, tol=0.0)
    eng = _engine(
        d, cfg, ((2, (48, 48)), (2, (16, 16))), tmp_path,
        staged_warmup=True, warm_order=("2@16x16",),
    )
    try:
        # constructor returned => hot bucket ready; the big cold
        # bucket is still compiling on the background thread
        assert eng.bucket_warm((2, (16, 16)))
        xc, mc = _req(48, seed=3)
        if not eng.bucket_warm((2, (48, 48))):
            with pytest.raises(BucketCold) as exc:
                eng.submit(xc * mc, mask=mc, x_orig=xc)
            assert exc.value.bucket == "2@48x48"
            assert exc.value.retry_after_s > 0
        # the hot bucket serves while the cold one builds
        x, m = _req(16, seed=2)
        res = eng.submit(x * m, mask=m, x_orig=x).result(timeout=120)
        assert res.bucket == "2@16x16"
        # the cold bucket finishes warming and then serves
        deadline = time.time() + 180
        while not eng.bucket_warm((2, (48, 48))):
            assert time.time() < deadline, "cold bucket never warmed"
            time.sleep(0.05)
        resc = eng.submit(
            xc * mc, mask=mc, x_orig=xc
        ).result(timeout=120)
        assert resc.bucket == "2@48x48"
    finally:
        eng.close()
    events = obs.read_events(str(tmp_path))
    stages = [e for e in events if e["type"] == "warmup_stage"]
    assert [e["stage"] for e in stages] == [1, 2]
    assert stages[0]["bucket"] == "2@16x16"
    ready = [e for e in events if e["type"] == "serve_ready"]
    assert ready[-1]["staged"] is True
    assert ready[-1]["first_ready_s"] <= ready[-1]["warmup_s"]


def test_staged_warm_order_typo_refused():
    d = _bank(k=4, s=3)
    with pytest.raises(CCSCInputError, match="not.*configured"):
        _engine(
            d, _cfg(max_it=3), ((2, (16, 16)),),
            staged_warmup=True, warm_order=("2@99x99",),
        )


def test_enable_compile_cache_latch_warns_on_different_path():
    """The per-process XLA cache latch: a SECOND enable call with a
    DIFFERENT path must warn on the obs console (tier=always) and
    keep the first path — silently honoring it would split compiles
    across two directories. Subprocess: the latch is process-global
    by design, so an in-process test would poison every other test's
    compile accounting."""
    import subprocess
    import sys

    code = """
import os, sys, tempfile
from ccsc_code_iccv2017_tpu.serve import enable_compile_cache
a = tempfile.mkdtemp(prefix="cc-a-")
b = tempfile.mkdtemp(prefix="cc-b-")
p1 = enable_compile_cache(a)
assert p1 == a, p1
p2 = enable_compile_cache(b)
assert p2 == a, p2
p3 = enable_compile_cache(a)  # same path: silent, still latched
assert p3 == a, p3
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True,
        text=True, env=env, timeout=240,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    warns = [
        ln for ln in p.stdout.splitlines()
        if "already latched" in ln
    ]
    assert len(warns) == 1, p.stdout
    assert "ignoring the new path" in warns[0]
