"""Supervised execution (utils.watchdog, scripts/supervise.py, the
auto-degrade ladder in apps._dispatch, restart-aware utils.faults).

The end-to-end contracts:

- under an injected crash (CCSC_FAULT_SIGTERM_IT) and an injected hang
  (CCSC_FAULT_HANG_IT), scripts/supervise.py restarts the learner from
  its checkpoint and the final dictionary matches an unfaulted run's
  trajectory — the kill/resume parity harness of
  tests/test_resilience.py, driven through the external supervisor;
- the --auto-degrade ladder steps donate -> smaller chunk -> streaming
  on a simulated HBM overflow, every downgrade visible in the obs
  event stream and in trace['degrades'];
- injected faults stay fire-once ACROSS supervisor restarts (the
  on-disk marker + fault_fired obs record, utils.faults);
- the watchdog derives its deadlines from the perfmodel bound, fires
  a `stall` event on a hung fence, and flags stale peer hosts.
"""
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt
from ccsc_code_iccv2017_tpu.utils import faults, obs, watchdog

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import supervise  # noqa: E402

sys.path.pop(0)


@pytest.fixture(autouse=True)
def _fault_isolation(monkeypatch):
    for v in (
        "CCSC_FAULT_NAN_IT",
        "CCSC_FAULT_CKPT_SAVE",
        "CCSC_FAULT_SIGTERM_IT",
        "CCSC_FAULT_HANG_IT",
        "CCSC_FAULT_HANG_S",
        "CCSC_FAULT_STATE_DIR",
        "CCSC_WATCHDOG_ACTION",
        "CCSC_WATCHDOG_MIN_S",
        "CCSC_WATCHDOG_COMPILE_S",
        "CCSC_INMEM_HBM_GB",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


GEOM = ProblemGeom((3, 3), 4)


def _data(seed=1, n=4, side=12):
    return np.array(
        jax.random.normal(jax.random.PRNGKey(seed), (n, side, side)),
        np.float32,
    )


def _cfg(**kw):
    base = dict(
        max_it=4, max_it_d=2, max_it_z=2, num_blocks=2,
        rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
        track_objective=True,
    )
    base.update(kw)
    return LearnConfig(**base)


def _assert_state_matches(dir_a, dir_b, atol=2e-5):
    # the kill/resume parity harness of tests/test_resilience.py
    fa, ta, ia = ckpt.load(dir_a)
    fb, tb, ib = ckpt.load(dir_b)
    assert ia == ib
    assert sorted(fa) == sorted(fb)
    for k in fa:  # includes the dual variables
        np.testing.assert_allclose(
            np.asarray(fa[k], np.float32), np.asarray(fb[k], np.float32),
            atol=atol, err_msg=k,
        )
    for k in ("obj_vals_d", "obj_vals_z", "d_diff", "z_diff"):
        np.testing.assert_allclose(ta[k], tb[k], rtol=1e-4, atol=1e-6)


def _worker_script(tmp_path, ck, mdir, watchdog_on=False):
    w = tmp_path / "worker.py"
    w.write_text(
        f"""
import sys
sys.path.insert(0, {REPO!r})
import jax, jax.numpy as jnp, numpy as np
from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
b = jnp.asarray(np.asarray(
    jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32))
cfg = LearnConfig(max_it=4, max_it_d=2, max_it_z=2, num_blocks=2,
                  rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
                  track_objective=True, watchdog={watchdog_on!r},
                  metrics_dir={str(mdir)!r})
learn(b, ProblemGeom((3, 3), 4), cfg, key=jax.random.PRNGKey(0),
      checkpoint_dir={str(ck)!r}, checkpoint_every=1)
"""
    )
    return str(w)


def _run_supervised(tmp_path, worker, ck, mdir, max_restarts=3):
    rc = supervise.main(
        [
            "--checkpoint-dir", str(ck),
            "--metrics-dir", str(mdir),
            "--max-restarts", str(max_restarts),
            "--backoff", "0",
            "--",
            sys.executable, worker,
        ]
    )
    trace = json.load(open(os.path.join(str(mdir), "supervisor_trace.json")))
    return rc, trace


# --------------------------------------------------------- e2e chaos tests


def test_supervised_sigterm_restart_matches_unfaulted(
    tmp_path, monkeypatch
):
    """Acceptance: injected crash (SIGTERM at iteration 2) -> the
    supervisor sees the preempted attempt, restarts from its
    checkpoint (fault fire-once across restarts), and the final
    dictionary state matches the unfaulted run's trajectory."""
    from ccsc_code_iccv2017_tpu.models.learn import learn

    ck_full = tmp_path / "full"
    learn(
        jnp.asarray(_data()), GEOM, _cfg(), key=jax.random.PRNGKey(0),
        checkpoint_dir=str(ck_full), checkpoint_every=1,
    )

    ck = tmp_path / "kill"
    mdir = tmp_path / "metrics"
    worker = _worker_script(tmp_path, ck, mdir)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("CCSC_FAULT_SIGTERM_IT", "2")
    rc, trace = _run_supervised(tmp_path, worker, ck, mdir)
    assert rc == 0, trace
    assert [a["reason"] for a in trace["attempts"]] == [
        "preempted", "completed",
    ]
    assert trace["outcome"] == "completed"
    _assert_state_matches(str(ck_full), str(ck))
    # the fault consumption is recorded, not process-global: the
    # marker file + the fault_fired record in the stream
    assert os.path.exists(str(mdir / "fault-fired-sigterm.json"))
    events = obs.read_events(str(mdir))
    fired = [e for e in events if e["type"] == "fault_fired"]
    assert any(e.get("fault") == "sigterm" for e in fired)


def test_supervised_hang_watchdog_abort_restart_matches(
    tmp_path, monkeypatch
):
    """Acceptance: injected hang (sleep inside the fence at iteration
    2) -> the in-process watchdog aborts with EXIT_STALL, the
    supervisor restarts from the iteration-1 checkpoint, the hang does
    not re-fire, and the final state matches the unfaulted run."""
    from ccsc_code_iccv2017_tpu.models.learn import learn

    ck_full = tmp_path / "full"
    learn(
        jnp.asarray(_data()), GEOM, _cfg(), key=jax.random.PRNGKey(0),
        checkpoint_dir=str(ck_full), checkpoint_every=1,
    )

    ck = tmp_path / "hang"
    mdir = tmp_path / "metrics"
    worker = _worker_script(tmp_path, ck, mdir, watchdog_on=True)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("CCSC_FAULT_HANG_IT", "2")
    monkeypatch.setenv("CCSC_FAULT_HANG_S", "3600")
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "3")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "120")
    rc, trace = _run_supervised(tmp_path, worker, ck, mdir)
    assert rc == 0, trace
    reasons = [a["reason"] for a in trace["attempts"]]
    assert reasons == ["stall_abort", "completed"], reasons
    assert trace["attempts"][0]["rc"] == watchdog.EXIT_STALL
    _assert_state_matches(str(ck_full), str(ck))
    events = obs.read_events(str(mdir))
    assert any(e["type"] == "stall" for e in events)
    assert any(
        e["type"] == "fault_fired" and e.get("fault") == "hang"
        for e in events
    )


def test_supervisor_poison_run_aborts_with_diagnosis(tmp_path, capsys):
    """Two consecutive deaths before the first checkpoint -> abort
    with a diagnosis instead of burning the restart budget."""
    rc = supervise.main(
        [
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--metrics-dir", str(tmp_path / "m"),
            "--max-restarts", "5",
            "--backoff", "0",
            "--",
            sys.executable, "-c",
            "import sys; print('dying in setup'); sys.exit(1)",
        ]
    )
    assert rc == supervise.EXIT_POISON
    trace = json.load(
        open(tmp_path / "m" / "supervisor_trace.json")
    )
    assert trace["outcome"] == "poison"
    assert [a["reason"] for a in trace["attempts"]] == ["crash", "crash"]
    out = capsys.readouterr().out
    assert "POISON RUN" in out
    assert "dying in setup" in out  # the log tail made it into the diagnosis


def test_supervisor_stall_kill(tmp_path):
    """A child that is alive but writes no progress is declared hung,
    killed and (being pre-checkpoint twice) poisons out."""
    t0 = time.monotonic()
    rc = supervise.main(
        [
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--metrics-dir", str(tmp_path / "m"),
            "--max-restarts", "4",
            "--backoff", "0",
            "--stall-timeout", "2",
            "--",
            sys.executable, "-c", "import time; time.sleep(600)",
        ]
    )
    assert rc == supervise.EXIT_POISON
    trace = json.load(open(tmp_path / "m" / "supervisor_trace.json"))
    assert [a["reason"] for a in trace["attempts"]] == ["hang", "hang"]
    assert time.monotonic() - t0 < 60  # killed, not slept out


# ------------------------------------------------------ auto-degrade ladder


def test_auto_degrade_ladder_steps_to_streaming(tmp_path, monkeypatch):
    """Acceptance: on a simulated HBM overflow (RESOURCE_EXHAUSTED at
    every in-memory dispatch) the ladder demonstrably steps donate ->
    smaller chunk -> streaming, with each downgrade in the obs event
    stream and in trace['degrades']."""
    import ccsc_code_iccv2017_tpu.models.learn as learn_mod
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn

    seen_cfgs = []

    def oom_learn(b, geom, cfg, **kw):
        seen_cfgs.append(cfg)
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating HBM "
            "(simulated overflow)"
        )

    # every in-memory attempt OOMs; the streaming learner is real
    monkeypatch.setattr(learn_mod, "learn", oom_learn)
    mdir = tmp_path / "metrics"
    cfg = _cfg(max_it=2, outer_chunk=4, metrics_dir=str(mdir))
    res = dispatch_learn(
        _data(), GEOM, cfg, jax.random.PRNGKey(0), None,
        streaming=False, auto_degrade=True,
    )
    rungs = [d["rung"] for d in res.trace["degrades"]]
    assert rungs == ["donate", "chunk", "streaming"]
    assert all(d["stage"] == "dispatch" for d in res.trace["degrades"])
    # each retry ran with the degraded config of its rung
    assert [
        (c.donate_state, c.outer_chunk) for c in seen_cfgs
    ] == [(False, 4), (True, 4), (True, 1)]
    # the run actually ran streaming
    assert res.trace["algorithm"] == "consensus_streaming"
    assert len(res.trace["obj_vals_z"]) == 3  # init + 2 iterations
    # every downgrade is visible in the obs event stream
    events = obs.read_events(str(mdir))
    degrades = [e for e in events if e["type"] == "degrade"]
    assert [e["rung"] for e in degrades] == ["donate", "chunk", "streaming"]


def test_auto_degrade_preflight_estimate_to_streaming(
    tmp_path, monkeypatch
):
    """Pre-flight overflow (the continue_3d-style estimate check):
    donate is tried first, and since a shorter scan cannot change the
    BYTE estimate the ladder goes straight to streaming — no sham
    'chunk' remediation in the telemetry."""
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn

    mdir = tmp_path / "metrics"
    monkeypatch.setenv("CCSC_INMEM_HBM_GB", "1e-9")  # ~1 byte budget
    cfg = _cfg(max_it=2, outer_chunk=4, metrics_dir=str(mdir))
    res = dispatch_learn(
        _data(), GEOM, cfg, jax.random.PRNGKey(0), None,
        streaming=False, auto_degrade=True,
    )
    rungs = [d["rung"] for d in res.trace["degrades"]]
    assert rungs == ["donate", "streaming"]
    assert all(d["stage"] == "preflight" for d in res.trace["degrades"])
    assert res.trace["algorithm"] == "consensus_streaming"
    degrades = [
        e for e in obs.read_events(str(mdir)) if e["type"] == "degrade"
    ]
    assert [e["rung"] for e in degrades] == ["donate", "streaming"]
    assert all("est_gb" in e and "budget_gb" in e for e in degrades)


def test_auto_degrade_streaming_rung_refuses_foreign_checkpoint(
    tmp_path, monkeypatch
):
    """A checkpoint already written by the in-memory learner is
    fingerprint-incompatible with learn_streaming; the ladder must
    stop BEFORE the streaming rung and surface the original OOM, not
    a confusing fingerprint refusal."""
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn
    from ccsc_code_iccv2017_tpu.models.learn import learn

    b, ck = _data(), tmp_path / "ck"
    learn(
        jnp.asarray(b), GEOM, _cfg(max_it=1), key=jax.random.PRNGKey(0),
        checkpoint_dir=str(ck), checkpoint_every=1,
    )

    def oom_learn(b, geom, cfg, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: simulated")

    import ccsc_code_iccv2017_tpu.models.learn as learn_mod

    monkeypatch.setattr(learn_mod, "learn", oom_learn)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        dispatch_learn(
            b, GEOM, _cfg(max_it=2), jax.random.PRNGKey(0), None,
            streaming=False, auto_degrade=True,
            checkpoint_dir=str(ck), checkpoint_every=1,
        )


def test_auto_degrade_preflight_stops_when_it_fits(monkeypatch):
    """A budget the donate rung satisfies stops the ladder there —
    the run keeps its in-memory strategy, just donated."""
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    b = _data()
    cfg = _cfg(max_it=1)
    est_donated, _ = perfmodel.inmem_learn_estimate(
        b.shape, GEOM, __import__("dataclasses").replace(
            cfg, donate_state=True
        )
    )
    est_plain, _ = perfmodel.inmem_learn_estimate(b.shape, GEOM, cfg)
    assert est_donated < est_plain  # donation drops the output copies
    # budget between the two estimates: exactly one rung fires
    monkeypatch.setenv(
        "CCSC_INMEM_HBM_GB", str((est_donated + 1) / 1e9)
    )
    res = dispatch_learn(
        b, GEOM, cfg, jax.random.PRNGKey(0), None,
        streaming=False, auto_degrade=True,
    )
    assert [d["rung"] for d in res.trace["degrades"]] == ["donate"]
    assert res.trace["algorithm"] == "consensus"


def test_auto_degrade_retries_on_resource_exhausted():
    """RESOURCE_EXHAUSTED at compile/first dispatch steps down a rung
    and retries; the retry runs with the degraded config."""
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn
    from ccsc_code_iccv2017_tpu.models.learn import learn

    seen_cfgs = []

    def flaky_solver(b, geom, cfg, **kw):
        seen_cfgs.append(cfg)
        if len(seen_cfgs) == 1:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 12345 "
                "bytes (simulated)"
            )
        return learn(b, geom, cfg, **kw)

    res = dispatch_learn(
        _data(), GEOM, _cfg(max_it=1), jax.random.PRNGKey(0), None,
        streaming=False, solver=flaky_solver, auto_degrade=True,
    )
    assert len(seen_cfgs) == 2
    assert not seen_cfgs[0].donate_state and seen_cfgs[1].donate_state
    assert [d["rung"] for d in res.trace["degrades"]] == ["donate"]
    assert res.trace["degrades"][0]["stage"] == "dispatch"


def test_auto_degrade_late_oom_with_progress_raises(tmp_path):
    """A runtime OOM AFTER iterations completed, with no checkpoint
    dir to resume from, must surface — silently restarting the learn
    from scratch would discard the completed work."""
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn

    mdir = tmp_path / "m"

    def late_oom_solver(b, geom, cfg, **kw):
        w = obs.EventWriter(str(mdir / "events-p00000.jsonl"))
        w.write({"t": time.time(), "type": "step", "it": 5, "host": 0})
        w.close()
        raise RuntimeError("RESOURCE_EXHAUSTED: late fragmentation")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        dispatch_learn(
            _data(), GEOM, _cfg(max_it=2, metrics_dir=str(mdir)),
            jax.random.PRNGKey(0), None, streaming=False,
            solver=late_oom_solver, auto_degrade=True,
        )


def test_auto_degrade_off_raises():
    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn

    def oom_solver(b, geom, cfg, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: simulated")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        dispatch_learn(
            _data(), GEOM, _cfg(max_it=1), jax.random.PRNGKey(0), None,
            streaming=False, solver=oom_solver,
        )


# --------------------------------------------------------- watchdog units


def test_watchdog_deadline_derivation(monkeypatch):
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "10")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "100")
    wd = watchdog.DispatchWatchdog(4.0, action="event")
    try:
        # first fence carries the compile allowance
        assert wd.timeout_for(1) == pytest.approx(110.0)
        wd.arm(1)
        wd.disarm()
        # later fences scale with the expected iterations, floored
        assert wd.timeout_for(1) == pytest.approx(10.0)
        assert wd.timeout_for(8) == pytest.approx(32.0)
        # a driver-signaled rebuild (partial tail chunk, post-recovery
        # rho rebuild) re-grants the compile allowance
        assert wd.timeout_for(8, may_compile=True) == pytest.approx(132.0)
    finally:
        wd.stop()
    # no cost model (masked/streaming): the floor scales with the
    # number of iterations the fence covers instead of being flat
    wd0 = watchdog.DispatchWatchdog(0.0, action="event")
    try:
        wd0.arm(1)
        wd0.disarm()
        assert wd0.timeout_for(1) == pytest.approx(10.0)
        assert wd0.timeout_for(16) == pytest.approx(160.0)
    finally:
        wd0.stop()


def test_watchdog_maybe_start_uses_perfmodel_bound():
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    cost = {"flops": 1e12, "bytes": 1e10}
    cfg = _cfg(watchdog=True, watchdog_slack=5.0)
    wd = watchdog.maybe_start(cfg, cost=cost)
    try:
        assert wd is not None
        bound = perfmodel.bound_iters_per_sec(cost)
        assert wd.per_iter_s == pytest.approx(5.0 / bound)
    finally:
        wd.stop()
    assert watchdog.maybe_start(_cfg()) is None  # off by default


def test_watchdog_stall_event_fires(tmp_path, monkeypatch):
    """An armed fence that never disarms produces a `stall` record in
    the obs stream (event mode: monitoring without authority)."""
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "0.3")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "0")
    run = obs.start_run(str(tmp_path), algorithm="test", verbose="none")
    wd = watchdog.DispatchWatchdog(0.0, action="event")
    try:
        wd.arm(1, "test_fence")
        deadline = time.monotonic() + 10
        while wd.stalls == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        wd.disarm()
    finally:
        wd.stop()
        run.close()
    assert wd.stalls >= 1
    events = obs.read_events(str(tmp_path))
    stalls = [e for e in events if e["type"] == "stall"]
    assert stalls and stalls[0]["label"] == "test_fence"


def test_hang_fault_learn_emits_stall_and_completes(
    tmp_path, monkeypatch
):
    """CCSC_FAULT_HANG_IT inside a real learn: the watchdog (event
    mode) records the stall and the run still completes when the
    injected hang ends — the CPU-provable watchdog contract."""
    from ccsc_code_iccv2017_tpu.models.learn import learn

    monkeypatch.setenv("CCSC_FAULT_HANG_IT", "2")
    monkeypatch.setenv("CCSC_FAULT_HANG_S", "1.5")
    monkeypatch.setenv("CCSC_WATCHDOG_ACTION", "event")
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "0.5")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "120")
    res = learn(
        jnp.asarray(_data()), GEOM,
        _cfg(watchdog=True, metrics_dir=str(tmp_path / "m")),
        key=jax.random.PRNGKey(0),
    )
    assert len(res.trace["obj_vals_z"]) == 5  # completed all 4 its
    events = obs.read_events(str(tmp_path / "m"))
    assert any(e["type"] == "stall" for e in events)


def test_check_peers_flags_stale_host(tmp_path):
    now = time.time()
    w0 = obs.EventWriter(str(tmp_path / "events-p00000.jsonl"))
    w1 = obs.EventWriter(str(tmp_path / "events-p00001.jsonl"))
    for t in (now - 500, now - 300, now - 10):
        w0.write({"t": t, "type": "heartbeat", "host": 0, "step": 1})
    # host 1 went quiet 400s before the stream's newest record
    w1.write({"t": now - 400, "type": "heartbeat", "host": 1, "step": 1})
    w0.close()
    w1.close()
    stale = watchdog.check_peers(str(tmp_path), stale_s=120)
    assert [p["host"] for p in stale] == [1]
    assert stale[0]["behind_s"] == pytest.approx(390, abs=5)
    # judged against the stream's own clock line: nothing stale when
    # every host stops together
    assert watchdog.check_peers(str(tmp_path), stale_s=1000) == []


def test_obs_report_liveness_column(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    now = time.time()
    w = obs.EventWriter(str(tmp_path / "events-p00000.jsonl"))
    w.write({"t": now - 500, "type": "heartbeat", "host": 0, "step": 1})
    w.write({"t": now - 490, "type": "heartbeat", "host": 1, "step": 1})
    w.write({"t": now, "type": "heartbeat", "host": 0, "step": 9})
    w.close()
    text = obs_report.render(
        obs.read_events(str(tmp_path)), stale_after=120
    )
    assert "host 0: live" in text
    assert "host 1: STALE" in text
    assert "watchdog would declare this host dead" in text


# ------------------------------------------------- restart-aware faults


def test_fault_fire_once_survives_process_restart(tmp_path, monkeypatch):
    """The fire-once contract persists in the state dir: after a
    simulated restart (faults.reset), an armed fault that already
    fired does not fire again."""
    monkeypatch.setenv("CCSC_FAULT_STATE_DIR", str(tmp_path))
    monkeypatch.setenv("CCSC_FAULT_CKPT_SAVE", "1")
    with pytest.raises(faults.InjectedFault):
        faults.ckpt_save_hook()
    assert os.path.exists(str(tmp_path / "fault-fired-ckpt.json"))
    faults.reset()  # a new process has empty in-memory state...
    faults.ckpt_save_hook()  # ...but the marker keeps it consumed
    # without a state dir the contract is process-local, as before
    monkeypatch.delenv("CCSC_FAULT_STATE_DIR")
    faults.reset()
    with pytest.raises(faults.InjectedFault):
        faults.ckpt_save_hook()


# -------------------------------------------- multi-dir / multi-child


def _write_events(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_attempt_preempted_judged_per_dir(tmp_path):
    """ISSUE 7 satellite: a fleet child has one stream per replica,
    judged PER DIR. Replica A's newest attempt was preempted; replica
    B restarted later (newer run_meta, no preemption). Per-dir
    judgment sees the preemption; a merged single-stream judgment
    would scope A's preemption to B's newer attempt and miss it."""
    a = tmp_path / "replica-00"
    b = tmp_path / "replica-01"
    t0 = time.time()
    _write_events(
        str(a / "events-p00000.jsonl"),
        [
            {"t": t0, "type": "run_meta"},
            {"t": t0 + 1, "type": "preemption", "iteration": 2},
        ],
    )
    _write_events(
        str(b / "events-p00000.jsonl"),
        [{"t": t0 + 5, "type": "run_meta"}, {"t": t0 + 6, "type": "step"}],
    )
    assert supervise._attempt_preempted([str(a), str(b)]) is True
    assert supervise._attempt_preempted([str(b)]) is False
    # the single merged stream WOULD have missed it — the reason the
    # supervisor takes a list
    merged = obs.read_events(str(a)) + obs.read_events(str(b))
    merged.sort(key=lambda e: e["t"])
    last_meta = max(
        i for i, e in enumerate(merged) if e.get("type") == "run_meta"
    )
    assert not any(
        e.get("type") == "preemption" for e in merged[last_meta + 1 :]
    )


def test_progress_stamp_sees_replica_subdirs(tmp_path):
    sub = tmp_path / "metrics" / "replica-00"
    os.makedirs(str(sub))
    s0 = supervise._progress_stamp([str(tmp_path / "metrics")])
    (sub / "events-p00000.jsonl").write_text('{"t": 1}\n')
    s1 = supervise._progress_stamp([str(tmp_path / "metrics")])
    assert s1 > s0  # a replica's stream write counts as progress


def test_multi_child_all_complete(tmp_path):
    """--child multi-child mode: two independent children, both
    complete, per-child traces written, fleet rc 0."""
    mdir = tmp_path / "m"
    rc = supervise.main(
        [
            "--metrics-dir", str(mdir),
            "--backoff", "0",
            "--child", f"{sys.executable} -c pass",
            "--child", f"{sys.executable} -c pass",
        ]
    )
    assert rc == 0
    for i in range(2):
        tr = json.load(
            open(
                os.path.join(
                    str(mdir), f"child-{i:02d}", "supervisor_trace.json"
                )
            )
        )
        assert tr["outcome"] == "completed"
        assert tr["label"] == f"child-{i:02d}"


def test_multi_child_sibling_failure_stops_fleet(tmp_path):
    """A terminally failing child (restart budget exhausted, no
    checkpoint) stops its long-running sibling; the fleet exits with
    the failing child's code and the sibling's trace says stopped."""
    mdir = tmp_path / "m"
    t0 = time.monotonic()
    rc = supervise.main(
        [
            "--metrics-dir", str(mdir),
            "--max-restarts", "0",
            "--backoff", "0",
            "--trace", str(tmp_path / "fleet_trace.json"),
            "--child", f"{sys.executable} -c 'import time; time.sleep(120)'",
            "--child", f"{sys.executable} -c 'raise SystemExit(1)'",
        ]
    )
    took = time.monotonic() - t0
    assert rc == supervise.EXIT_EXHAUSTED
    assert took < 60, "the sleeping sibling must be stopped, not waited out"
    tr0 = json.load(
        open(os.path.join(str(mdir), "child-00", "supervisor_trace.json"))
    )
    tr1 = json.load(
        open(os.path.join(str(mdir), "child-01", "supervisor_trace.json"))
    )
    assert tr0["outcome"] == "stopped"
    assert tr0["attempts"][-1]["reason"] == "fleet_stop"
    assert tr1["outcome"] == "exhausted"
    fleet_tr = json.load(open(str(tmp_path / "fleet_trace.json")))
    assert fleet_tr["rc"] == supervise.EXIT_EXHAUSTED
    assert {c["label"]: c["outcome"] for c in fleet_tr["children"]} == {
        "child-00": "stopped", "child-01": "exhausted"
    }


def test_multi_child_dir_pairing_usage_error(tmp_path, capsys):
    rc = supervise.main(
        [
            "--metrics-dir", str(tmp_path / "a"),
            "--metrics-dir", str(tmp_path / "b"),
            "--metrics-dir", str(tmp_path / "c"),
            "--child", f"{sys.executable} -c pass",
            "--child", f"{sys.executable} -c pass",
        ]
    )
    assert rc == supervise.EXIT_USAGE
    # and --child is mutually exclusive with a trailing command
    rc = supervise.main(
        [
            "--child", f"{sys.executable} -c pass",
            "--", sys.executable, "-c", "pass",
        ]
    )
    assert rc == supervise.EXIT_USAGE
