"""Integration tests for the consensus learner (CPU, virtual 8-device
mesh — SURVEY.md section 4's fake-cluster strategy)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh


def _toy_data(n=8, size=20, seed=0):
    """Images synthesized from a ground-truth 2-filter dictionary so
    learning has structure to find."""
    r = np.random.default_rng(seed)
    imgs = []
    for _ in range(n):
        x = np.zeros((size, size), np.float32)
        for _ in range(6):
            i, j = r.integers(2, size - 2, 2)
            x[i, j] = r.normal()
        # blur with a random edge filter
        from scipy.signal import convolve2d

        f = r.normal(size=(3, 3)).astype(np.float32)
        imgs.append(convolve2d(x, f, mode="same"))
    return jnp.asarray(np.stack(imgs))


CFG = dict(
    max_it=4,
    max_it_d=3,
    max_it_z=3,
    rho_d=500.0,
    rho_z=10.0,
    lambda_prior=0.1,
    verbose="none",
    track_objective=True,
)


def test_objective_decreases():
    b = _toy_data()
    geom = ProblemGeom((5, 5), 8)
    res = learn(b, geom, LearnConfig(num_blocks=2, **CFG))
    obj = res.trace["obj_vals_z"]
    assert obj[-1] < 0.5 * obj[0]
    # filters feasible: unit ball, support preserved
    norms = np.sqrt(np.sum(np.asarray(res.d) ** 2, axis=(1, 2)))
    assert np.all(norms <= 1.0 + 1e-4)
    assert res.d.shape == (8, 5, 5)
    assert res.Dz.shape == b.shape


def test_mesh_matches_single_device():
    """Consensus over a sharded 'block' mesh must reproduce the local
    path exactly — the collective IS the cell-array sum
    (dzParallel.m:115-121 -> psum)."""
    b = _toy_data()
    geom = ProblemGeom((5, 5), 8)
    cfg = LearnConfig(num_blocks=4, **CFG)
    res_local = learn(b, geom, cfg)
    res_mesh = learn(b, geom, cfg, mesh=block_mesh(4))
    np.testing.assert_allclose(
        np.asarray(res_local.d), np.asarray(res_mesh.d), atol=2e-5
    )
    np.testing.assert_allclose(
        res_local.trace["obj_vals_z"],
        res_mesh.trace["obj_vals_z"],
        rtol=1e-4,
    )


def test_blocks_per_device_gt_one():
    """N=8 blocks on a 4-device mesh (L=2 per device)."""
    b = _toy_data()
    geom = ProblemGeom((5, 5), 4)
    cfg = LearnConfig(num_blocks=8, **CFG)
    res_local = learn(b, geom, cfg)
    res_mesh = learn(b, geom, cfg, mesh=block_mesh(4))
    np.testing.assert_allclose(
        np.asarray(res_local.d), np.asarray(res_mesh.d), atol=2e-5
    )


def test_learn_3d_geometry():
    """Dimension-generic: 3 spatial FFT dims (the 3D video learner,
    3D/admm_learn_conv3D_large.m)."""
    r = np.random.default_rng(3)
    b = jnp.asarray(r.normal(size=(4, 10, 10, 10)).astype(np.float32))
    geom = ProblemGeom((3, 3, 3), 4)
    res = learn(b, geom, LearnConfig(num_blocks=2, **CFG))
    assert res.d.shape == (4, 3, 3, 3)
    obj = res.trace["obj_vals_z"]
    assert obj[-1] < obj[0]


def test_learn_reduce_geometry():
    """Wavelength-shared codes (the 2-3D hyperspectral learner,
    2-3D/DictionaryLearning/admm_learn.m:13-16): filters carry a
    4-wavelength axis, codes are 2-D."""
    r = np.random.default_rng(4)
    b = jnp.asarray(r.normal(size=(4, 4, 12, 12)).astype(np.float32))
    geom = ProblemGeom((5, 5), 6, reduce_shape=(4,))
    res = learn(b, geom, LearnConfig(num_blocks=2, **CFG))
    assert res.d.shape == (6, 4, 5, 5)
    obj = res.trace["obj_vals_z"]
    assert obj[-1] < obj[0]
    assert res.z.shape[2] == 6  # codes have no wavelength axis


def test_block_freq_mesh_matches_single_device():
    """DP x TP: 2-D ('block','freq') mesh — frequency-sharded solves
    with all_gather reassembly — must match the local path exactly."""
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_freq_mesh

    b = _toy_data()
    geom = ProblemGeom((5, 5), 8)
    cfg = LearnConfig(num_blocks=2, **CFG)
    res_local = learn(b, geom, cfg)
    res_mesh = learn(b, geom, cfg, mesh=block_freq_mesh(2, 4))
    np.testing.assert_allclose(
        np.asarray(res_local.d), np.asarray(res_mesh.d), atol=2e-5
    )
    np.testing.assert_allclose(
        res_local.trace["obj_vals_z"],
        res_mesh.trace["obj_vals_z"],
        rtol=1e-4,
    )


def test_warm_start_init_d():
    """init_d seeds every block's dictionary and the consensus average
    (the intent of the reference's unused `init` param, dParallel.m:4 /
    admm_learn.m:50-58): resuming from learned filters starts at a far
    lower objective than a random init."""
    b = _toy_data()
    geom = ProblemGeom((5, 5), 8)
    cfg = LearnConfig(num_blocks=2, **CFG)
    first = learn(b, geom, cfg)
    warm = learn(b, geom, LearnConfig(num_blocks=2, **{**CFG, "max_it": 1}),
                 init_d=first.d)
    cold = learn(b, geom, LearnConfig(num_blocks=2, **{**CFG, "max_it": 1}))
    # codes start random either way (the d-pass precedes the z-pass, so
    # one outer iteration largely equalizes the objective); the warm
    # start shows up as a lower initial objective...
    assert warm.trace["obj_vals_z"][0] < cold.trace["obj_vals_z"][0]
    # ...and a zero-iteration run returns the seeded dictionary itself
    # (already feasible, so the projection is a no-op)
    seeded = learn(
        b, geom, LearnConfig(num_blocks=2, **{**CFG, "max_it": 0}),
        init_d=first.d,
    )
    np.testing.assert_allclose(
        np.asarray(seeded.d), np.asarray(first.d), atol=1e-5
    )
    with pytest.raises(ValueError):
        learn(b, geom, cfg, init_d=jnp.zeros((3, 5, 5)))


def test_nan_guard_keeps_last_good_state(monkeypatch):
    """Failure detection: a diverging run (non-finite metrics) stops and
    returns the last finite state instead of NaNs.

    Poisoned via the sanctioned chaos point (CCSC_FAULT_NAN_IT inside
    the jitted step) — non-finite INPUT data is now rejected at the
    entry boundary by utils.validate, so it can no longer be used as a
    divergence trigger."""
    from ccsc_code_iccv2017_tpu.utils import faults

    geom = ProblemGeom((3, 3), 4)
    b = np.array(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    cfg = LearnConfig(
        max_it=3, max_it_d=1, max_it_z=1, num_blocks=2,
        rho_d=50.0, rho_z=2.0, verbose="none", track_objective=True,
    )
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")
    faults.reset()
    try:
        res = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0))
    finally:
        faults.reset()
    # result is the pre-divergence state: everything finite
    assert np.isfinite(np.asarray(res.d)).all()
    assert np.isfinite(np.asarray(res.z)).all()


def test_learn_masked_freq_mesh_matches():
    """Masked hyperspectral learner with frequency-axis TP == local."""
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked
    from ccsc_code_iccv2017_tpu.parallel.mesh import freq_mesh

    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    cfg = LearnConfig(
        max_it=2, max_it_d=2, max_it_z=2, verbose="none",
        lambda_residual=1.0, lambda_prior=1.0,
    )
    r = np.random.default_rng(0)
    # padded 8+2 -> 10x10 rfft = (10, 6) -> F=60, divisible by 4
    b = r.uniform(0.1, 1.0, (2, 2, 8, 8)).astype(np.float32)
    kw = dict(gamma_div_d=50.0, gamma_div_z=10.0, key=jax.random.PRNGKey(0))
    res_l = learn_masked(jnp.asarray(b), geom, cfg, **kw)
    res_m = learn_masked(jnp.asarray(b), geom, cfg, mesh=freq_mesh(4), **kw)
    np.testing.assert_allclose(
        np.asarray(res_l.d), np.asarray(res_m.d), atol=2e-5
    )
    np.testing.assert_allclose(
        res_l.trace["obj_vals_z"], res_m.trace["obj_vals_z"], rtol=1e-4
    )


def test_block_filter_mesh_matches_single_device():
    """DP x filter-TP: ('block','filter') mesh — k-sharded filters,
    codes, and duals with one psum per k-reduction — must match the
    local path exactly (SURVEY section 2.5 third axis; the k-loop seam
    at dParallel.m:278-303)."""
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_filter_mesh

    b = _toy_data()
    geom = ProblemGeom((5, 5), 8)
    cfg = LearnConfig(num_blocks=2, **CFG)
    res_local = learn(b, geom, cfg)
    res_mesh = learn(b, geom, cfg, mesh=block_filter_mesh(2, 4))
    np.testing.assert_allclose(
        np.asarray(res_local.d), np.asarray(res_mesh.d), atol=2e-5
    )
    np.testing.assert_allclose(
        res_local.trace["obj_vals_z"],
        res_mesh.trace["obj_vals_z"],
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        res_local.trace["obj_vals_d"],
        res_mesh.trace["obj_vals_d"],
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(res_local.Dz), np.asarray(res_mesh.Dz), atol=2e-5
    )


def test_filter_mesh_reduce_geometry():
    """Filter sharding with W > 1 (hyperspectral-style reduce dims):
    the W x W inner system path also k-psums correctly."""
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_filter_mesh

    key = jax.random.PRNGKey(3)
    b = jax.random.normal(key, (4, 2, 12, 12), jnp.float32)
    geom = ProblemGeom((3, 3), 4, reduce_shape=(2,))
    cfg = LearnConfig(num_blocks=2, **CFG)
    res_local = learn(b, geom, cfg)
    res_mesh = learn(b, geom, cfg, mesh=block_filter_mesh(2, 2))
    np.testing.assert_allclose(
        np.asarray(res_local.d), np.asarray(res_mesh.d), atol=2e-5
    )
    np.testing.assert_allclose(
        res_local.trace["obj_vals_z"],
        res_mesh.trace["obj_vals_z"],
        rtol=1e-4,
    )


def test_fft_pad_fast_domain():
    """fft_pad rounds the FFT domain up (110-style sizes -> pow2) while
    keeping the data at offset radius. At a size where padding is
    already a power of two the result is bit-identical to 'none'; at an
    awkward size the learner still converges and produces filters close
    to the exact-domain run."""
    r = np.random.default_rng(3)
    geom = ProblemGeom((5, 5), 6)
    cfg_kw = dict(
        max_it=3, max_it_d=3, max_it_z=3, num_blocks=2,
        rho_d=500.0, rho_z=10.0, lambda_prior=0.5,
        verbose="none", track_objective=True,
    )
    # 12 + 2*2 = 16 = 2^4: fast domain == exact domain, identical run
    b16 = r.normal(size=(4, 12, 12)).astype(np.float32)
    r_none = learn(
        jnp.asarray(b16), geom, LearnConfig(**cfg_kw),
        key=jax.random.PRNGKey(0),
    )
    r_pow2 = learn(
        jnp.asarray(b16), geom, LearnConfig(**cfg_kw, fft_pad="pow2"),
        key=jax.random.PRNGKey(0),
    )
    np.testing.assert_allclose(
        np.asarray(r_none.d), np.asarray(r_pow2.d), atol=1e-6
    )
    np.testing.assert_allclose(
        r_none.trace["obj_vals_z"], r_pow2.trace["obj_vals_z"], rtol=1e-6
    )
    # 13 + 4 = 17 -> pow2 32: converges on the padded canvas
    b17 = r.normal(size=(4, 13, 13)).astype(np.float32)
    r_fast = learn(
        jnp.asarray(b17), geom, LearnConfig(**cfg_kw, fft_pad="pow2"),
        key=jax.random.PRNGKey(0),
    )
    assert r_fast.Dz.shape == (4, 13, 13)
    assert r_fast.d.shape == (6, 5, 5)
    obj = r_fast.trace["obj_vals_z"]
    assert obj[-1] < obj[0]


def test_bf16_storage_trajectory_close_to_f32():
    """storage_dtype='bfloat16' keeps z/dual_z in bf16 (half the HBM
    bytes of the dominant tensors) with all math in f32. The golden-2D
    trajectory must track the f32 run closely — the stored iterate is
    the only thing rounded."""
    r = np.random.default_rng(7)
    b = r.normal(size=(4, 16, 16)).astype(np.float32)
    geom = ProblemGeom((5, 5), 6)
    kw = dict(
        max_it=4, max_it_d=3, max_it_z=3, num_blocks=2,
        rho_d=500.0, rho_z=10.0, lambda_prior=0.5,
        verbose="none", track_objective=True,
    )
    r32 = learn(
        jnp.asarray(b), geom, LearnConfig(**kw),
        key=jax.random.PRNGKey(42),
    )
    r16 = learn(
        jnp.asarray(b), geom,
        LearnConfig(**kw, storage_dtype="bfloat16"),
        key=jax.random.PRNGKey(42),
    )
    assert r16.z.dtype == jnp.bfloat16
    o32 = np.asarray(r32.trace["obj_vals_z"], np.float64)
    o16 = np.asarray(r16.trace["obj_vals_z"], np.float64)
    dev = np.max(np.abs(o32 - o16) / np.abs(o32))
    assert dev < 0.02, f"bf16 trajectory deviates {dev:.3%}"
    d_err = np.max(np.abs(np.asarray(r32.d) - np.asarray(r16.d, np.float32)))
    assert d_err < 0.05 * np.abs(np.asarray(r32.d)).max()


def test_fft_impl_matmul_matches_xla():
    """The matmul-DFT execution strategy (fft_impl='matmul') reproduces
    the jnp.fft learner trajectory to float tolerance — same problem,
    same math, different kernels (PERF.md r4: the MXU-side FFT lever)."""
    b = _toy_data(n=8, size=20, seed=5)
    geom = ProblemGeom((5, 5), 6)
    kw = dict(CFG, num_blocks=2)
    r_xla = learn(
        b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(2)
    )
    r_mm = learn(
        b, geom, LearnConfig(**kw, fft_impl="matmul"),
        key=jax.random.PRNGKey(2),
    )
    np.testing.assert_allclose(
        np.asarray(r_xla.d), np.asarray(r_mm.d), atol=2e-4
    )
    np.testing.assert_allclose(
        r_xla.trace["obj_vals_z"], r_mm.trace["obj_vals_z"], rtol=2e-4
    )


def test_d_bf16_storage_trajectory_close_to_f32():
    """bf16 storage of the per-block dictionary state (d_storage_dtype)
    tracks the f32 trajectory closely — same contract as the code-state
    knob (f32 math, only the stored iterate rounded)."""
    b = _toy_data(n=8, size=20, seed=9)
    geom = ProblemGeom((5, 5), 6)
    kw = dict(CFG, num_blocks=2, max_it=8)
    r32 = learn(b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(3))
    r16 = learn(
        b, geom, LearnConfig(**kw, d_storage_dtype="bfloat16"),
        key=jax.random.PRNGKey(3),
    )
    o32 = np.asarray(r32.trace["obj_vals_z"], np.float64)
    o16 = np.asarray(r16.trace["obj_vals_z"], np.float64)
    dev = np.max(np.abs(o32 - o16) / np.abs(o32))
    assert dev < 0.02, f"d-state bf16 trajectory deviates {dev:.3%}"
    d_err = np.max(np.abs(np.asarray(r32.d) - np.asarray(r16.d, np.float32)))
    assert d_err < 0.05 * np.abs(np.asarray(r32.d)).max()
