"""Independent NumPy oracle of the masked (hyperspectral) learner.

Dense re-derivation of models/learn_masked.py::_outer_step — the
reference's non-consensus 2-function ADMM with masked data prox,
smooth_init offset and gamma heuristic
(2-3D/DictionaryLearning/admm_learn.m:102-136 d-pass, :165-200 z-pass)
— with W > 1 reduce (wavelength) dims, full complex FFTs and dense
per-frequency ``np.linalg.solve`` (no Woodbury), checked
state-for-state against the jitted step. This pins the wavelength-
shared-code geometry (admm_learn.m:13-16) at trajectory level; the
per-call W > 1 solves are covered in tests/test_ops.py.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn_masked
from ccsc_code_iccv2017_tpu.ops import fourier

from test_oracle_trajectory import _circ_embed_np, _circ_extract_np, _soft_np


def _kernel_proj_np(d_full, support, spatial_shape):
    """Per (filter, reduce-slice) unit-ball projection, spatial norms
    only (2-3D admm_learn.m:246)."""
    ndim_s = len(support)
    d_sup = _circ_extract_np(d_full, support)
    axes = tuple(range(d_sup.ndim - ndim_s, d_sup.ndim))
    sq = np.sum(d_sup * d_sup, axis=axes, keepdims=True)
    scale = np.where(sq >= 1.0, 1.0 / np.sqrt(np.maximum(sq, 1e-30)), 1.0)
    return _circ_embed_np(d_sup * scale, spatial_shape)


def oracle_masked_step(
    state, b_pad, M_pad, smoothinit, geom, cfg, spatial, gdd, gdz
):
    n = b_pad.shape[0]
    K = geom.num_filters
    W = geom.reduce_size
    ndim_s = len(spatial)
    fft_axes = tuple(range(-ndim_s, 0))
    F = int(np.prod(spatial))

    d_full, du_d1, du_d2, z, du_z1, du_z2 = [
        np.array(v, np.float64) for v in state
    ]

    g = 60.0 * cfg.lambda_prior / max(np.max(M_pad * b_pad), 1e-30)
    Mtb = (b_pad - smoothinit) * M_pad
    MtM = M_pad * M_pad
    rho_d, rho_z = float(gdd), float(gdz)

    def mprox(u, theta):
        return (Mtb + u / theta) / (MtM + 1.0 / theta)

    def fftF(x, lead):
        return np.fft.fftn(x, axes=fft_axes).reshape(*lead, -1)

    zhat = fftF(z, (n, K))  # fixed through the d-pass

    for _ in range(cfg.max_it_d):
        dhat = fftF(d_full, (K, W))
        v1 = np.real(
            np.fft.ifftn(
                np.einsum("kwf,nkf->nwf", dhat, zhat).reshape(
                    n, W, *spatial
                ),
                axes=fft_axes,
            )
        ).reshape(b_pad.shape)
        u1 = mprox(v1 - du_d1, cfg.lambda_residual / (g / gdd))
        u2 = _kernel_proj_np(d_full - du_d2, geom.spatial_support, spatial)
        du_d1 = du_d1 - (v1 - u1)
        du_d2 = du_d2 - (d_full - u2)
        xi1_hat = fftF((u1 + du_d1).reshape(n, W, *spatial), (n, W))
        xi2_hat = fftF(u2 + du_d2, (K, W))
        dnew_hat = np.empty_like(xi2_hat)
        for f in range(F):
            Z = zhat[:, :, f]  # [n, K]
            A = rho_d * np.eye(K) + Z.conj().T @ Z
            for w in range(W):
                rhs = Z.conj().T @ xi1_hat[:, w, f] + rho_d * xi2_hat[:, w, f]
                dnew_hat[:, w, f] = np.linalg.solve(A, rhs)
        d_full = np.real(
            np.fft.ifftn(
                dnew_hat.reshape(K, W, *spatial), axes=fft_axes
            )
        ).reshape(d_full.shape)

    dhat = fftF(d_full, (K, W))

    for _ in range(cfg.max_it_z):
        zh = fftF(z, (n, K))
        v1 = np.real(
            np.fft.ifftn(
                np.einsum("kwf,nkf->nwf", dhat, zh).reshape(n, W, *spatial),
                axes=fft_axes,
            )
        ).reshape(b_pad.shape)
        u1 = mprox(v1 - du_z1, cfg.lambda_residual / (g / gdz))
        u2 = _soft_np(z - du_z2, cfg.lambda_prior / g)
        du_z1 = du_z1 - (v1 - u1)
        du_z2 = du_z2 - (z - u2)
        xi1_hat = fftF((u1 + du_z1).reshape(n, W, *spatial), (n, W))
        xi2_hat = fftF(u2 + du_z2, (n, K))
        znew_hat = np.empty_like(xi2_hat)
        for ni_ in range(n):
            for f in range(F):
                A_f = dhat[:, :, f].T  # [W, K]
                M = rho_z * np.eye(K) + A_f.conj().T @ A_f
                rhs = (
                    A_f.conj().T @ xi1_hat[ni_, :, f]
                    + rho_z * xi2_hat[ni_, :, f]
                )
                znew_hat[ni_, :, f] = np.linalg.solve(M, rhs)
        z = np.real(
            np.fft.ifftn(znew_hat.reshape(n, K, *spatial), axes=fft_axes)
        )

    return d_full, du_d1, du_d2, z, du_z1, du_z2


def test_masked_learner_matches_numpy_oracle():
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    cfg = LearnConfig(
        max_it=2,
        max_it_d=2,
        max_it_z=2,
        lambda_residual=1.0,
        lambda_prior=1.0,
        verbose="none",
    )
    gdd, gdz = 50.0, 10.0
    n, size = 2, 8
    fg = common.FreqGeom.create(geom, (size, size))

    r = np.random.default_rng(0)
    b = r.uniform(0.1, 1.0, (n, 2, size, size)).astype(np.float32)
    sm = r.uniform(0.0, 0.2, b.shape).astype(np.float32)

    radius = geom.psf_radius
    b_pad = np.asarray(fourier.pad_spatial(jnp.asarray(b), radius))
    M_pad = np.asarray(
        fourier.pad_spatial(jnp.ones_like(jnp.asarray(b)), radius)
    )
    smoothinit = np.asarray(
        fourier.pad_spatial(jnp.asarray(sm), radius, mode="symmetric")
    )

    d0 = r.normal(size=(3, 2, 3, 3)).astype(np.float32)
    d_full = np.asarray(
        fourier.circ_embed(jnp.asarray(d0), fg.spatial_shape)
    )
    z0 = r.normal(size=(n, 3, *fg.spatial_shape)).astype(np.float32)
    x_shape = (n, 2, *fg.spatial_shape)
    state = learn_masked.MaskedLearnState(
        jnp.asarray(d_full),
        jnp.zeros(x_shape, jnp.float32),
        jnp.zeros_like(jnp.asarray(d_full)),
        jnp.asarray(z0),
        jnp.zeros(x_shape, jnp.float32),
        jnp.zeros_like(jnp.asarray(z0)),
    )
    np_state = tuple(np.array(v, np.float64) for v in state)

    for it in range(cfg.max_it):
        state, *_ = learn_masked._outer_step(
            state,
            jnp.asarray(b_pad),
            jnp.asarray(M_pad),
            jnp.asarray(smoothinit),
            geom,
            cfg,
            fg,
            gdd,
            gdz,
        )
        np_state = oracle_masked_step(
            np_state, b_pad, M_pad, smoothinit, geom, cfg,
            fg.spatial_shape, gdd, gdz,
        )
        for name, a, o in zip(
            learn_masked.MaskedLearnState._fields, state, np_state
        ):
            np.testing.assert_allclose(
                np.asarray(a, np.float64),
                o,
                atol=5e-4,
                rtol=5e-4,
                err_msg=f"outer iter {it}, field {name}",
            )


def test_masked_learner_fft_pad_and_bf16():
    """fft_pad + bf16 storage on the masked learner: fast-domain run
    converges with the mask excluding all padding, and the bf16 run
    tracks the f32 trajectory closely."""
    lm = learn_masked.learn_masked
    r = np.random.default_rng(17)
    # 26 + 2*2 = 30 -> pow2 32: genuine extra padding
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 3, 26, 26)), jnp.float32)
    geom = ProblemGeom((5, 5), 4, (3,))
    # 5/5 inner iterations: enough descent per pass that the rollback
    # guard (admm_learn.m:204-213) never fires on this toy config
    kw = dict(max_it=3, max_it_d=5, max_it_z=5, tol=0.0, verbose="none",
              track_objective=True)
    r_none = lm(b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(1))
    r_fast = lm(
        b, geom, LearnConfig(**kw, fft_pad="pow2"), key=jax.random.PRNGKey(1)
    )
    assert r_fast.Dz.shape == r_none.Dz.shape == (2, 3, 26, 26)
    o = r_fast.trace["obj_vals_z"]
    assert o[-1] < o[0]
    r_16 = lm(
        b, geom, LearnConfig(**kw, storage_dtype="bfloat16"),
        key=jax.random.PRNGKey(1),
    )
    o32 = np.asarray(r_none.trace["obj_vals_z"], np.float64)
    o16 = np.asarray(r_16.trace["obj_vals_z"], np.float64)
    m = min(len(o32), len(o16))
    assert m >= 2
    dev = np.max(np.abs(o32[:m] - o16[:m]) / np.abs(o32[:m]))
    assert dev < 0.02, dev


def test_masked_learner_fft_impl_matmul():
    """fft_impl='matmul' reproduces the masked learner's trajectory to
    float tolerance (W>1 geometry — the spatial FFT axes go through
    the DFT-matmul path, the wavelength axis stays a reduce axis)."""
    lm = learn_masked.learn_masked
    r = np.random.default_rng(23)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 3, 20, 20)), jnp.float32)
    geom = ProblemGeom((5, 5), 4, (3,))
    kw = dict(max_it=2, max_it_d=3, max_it_z=3, tol=0.0, verbose="none",
              track_objective=True)
    r_xla = lm(b, geom, LearnConfig(**kw), key=jax.random.PRNGKey(2))
    r_mm = lm(
        b, geom, LearnConfig(**kw, fft_impl="matmul"),
        key=jax.random.PRNGKey(2),
    )
    np.testing.assert_allclose(
        np.asarray(r_xla.d), np.asarray(r_mm.d), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(r_xla.trace["obj_vals_z"]),
        np.asarray(r_mm.trace["obj_vals_z"]),
        rtol=2e-4,
    )
