# known-bad fixture for the thread-safety check (exact lines pinned
# by tests/test_analysis.py — keep line numbers stable)
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def takes_a_then_b():
    with _lock_a:
        with _lock_b:  # L11: order a -> b
            pass


def takes_b_then_a():
    with _lock_b:
        with _lock_a:  # L17: order b -> a (inversion)
            pass


class Worker:
    def __init__(self, run):
        self._lock = threading.Lock()
        self._run = run

    def emits_under_lock(self):
        with self._lock:
            self._run.event("serve_drain", replica_id=0, n=1)  # L27

    def sleeps_under_lock(self):
        import time

        with self._lock:
            time.sleep(0.5)  # L33: blocking under the mutex

    def fire_and_forget(self):
        threading.Thread(target=self.emits_under_lock).start()  # L36
