# known-clean fixture for the obs-schema SPAN conventions: every
# span_end's literal span name has a matching span_start emitter, and
# both carry the full trace context (trace_id/span/span_id/replica_id
# plus status on the end).


def emit_sites(run):
    run.event(
        "span_start",
        trace_id="t1",
        span="solve",
        span_id="s1",
        parent_span="root1",
        replica_id=0,
        ts=123.0,
    )
    run.event(
        "span_end",
        trace_id="t1",
        span="solve",
        span_id="s1",
        parent_span="root1",
        replica_id=0,
        status="ok",
        ts=124.0,
    )
