# known-bad fixture for the donation-safety check
import jax


def make_step(f):
    return jax.jit(f, donate_argnums=(0,))


def bad_driver(state, data):
    step = make_step(lambda s, d: s)
    new_state, aux = step(state, data)
    total = state.sum()  # L12: read of the donated (dead) buffer
    return new_state, total, aux


def bad_direct(state, f):
    g = jax.jit(f, donate_argnums=(0,))
    out = g(state)
    return out + state  # L19: read of the donated (dead) buffer
