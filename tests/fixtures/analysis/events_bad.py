# known-bad fixture for the obs-schema check


def emit_sites(run):
    run.event("serve_request", bucket="4@64x64")  # L5: missing fields
    run.event("totally_new_event", value=1)  # L6: undeclared event


def writer_site(writer):
    import time

    writer.write({"t": time.time(), "type": "bogus_record", "x": 1})  # L11


def consumer(events):
    return [e for e in events if e.get("type") == "never_emitted"]  # L15
