# known-bad fixture: an app CLI that skips the validate boundary


def main(argv=None):
    print("apps may print")  # apps/ is exempt from bare-print
    return 0
