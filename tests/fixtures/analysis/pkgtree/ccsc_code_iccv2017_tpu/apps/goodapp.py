# known-clean fixture: an app CLI that routes through utils.validate
from ..utils import validate


def main(argv=None):
    data = [1.0]
    validate.check_finite("data", data)
    return 0
