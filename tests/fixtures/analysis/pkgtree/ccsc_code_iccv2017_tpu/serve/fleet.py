# known-clean fixture: every event rides the stamping _emit


class Fleet:
    def __init__(self, run):
        self._run = run

    def _emit(self, type_, *, replica_id, **fields):
        self._run.event(type_, replica_id=replica_id, **fields)

    def beat(self, rep):
        self._emit(
            "fleet_heartbeat", replica_id=rep, state="live",
            served=0, restarts=0,
        )
