# known-bad fixture: a serve module with a direct event bypassing
# the replica_id-stamping _emit


class Engine:
    def __init__(self, run):
        self._run = run
        self._replica_id = 0

    def _emit(self, type_, **fields):
        self._run.event(type_, replica_id=self._replica_id, **fields)

    def good(self):
        self._emit("serve_drain", n=1)

    def bad(self):
        self._run.event("serve_error", replica_id=0, error="x")  # L17
