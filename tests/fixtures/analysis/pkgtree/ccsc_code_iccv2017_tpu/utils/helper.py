# known-bad fixture: bare print in library code


def report(msg):
    print(msg)  # L5: bare-print finding


def quiet(msg):
    from . import obs

    obs.console(msg, tier="brief")
