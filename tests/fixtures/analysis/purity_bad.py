# known-bad fixture for the jit-purity check (tests/test_analysis.py
# pins the exact finding lines — keep line numbers stable)
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def hot_step(x):
    t = time.time()  # L13: host clock read
    v = float(x.sum().item())  # L14: host sync
    if jnp.any(x > 0):  # L15: python branch on a traced value
        x = x + v + t
    print("step done")  # L17: host print
    knob = os.environ.get("CCSC_HERM_INV")  # L18: env read
    return helper(x), knob


def helper(x):
    # reachable from hot_step -> hazards flagged here too
    return np.asarray(x)  # L24: numpy materialization


def scanned_body(carry, _):
    carry = carry + time.perf_counter()  # L28: host clock in scan body
    return carry, None


def run_scan(x):
    out, _ = jax.lax.scan(scanned_body, x, None, length=3)
    return out
