# known-clean fixture for the env-registry check
import os

from ccsc_code_iccv2017_tpu.utils import env


def declared_reads():
    return (
        env.env_float("CCSC_WATCHDOG_MIN_S"),
        env.env_str("CCSC_COMPILE_CACHE"),
        env.env_flag("CCSC_FAULT_CKPT_SAVE"),
    )


def writes_are_exempt(tmp):
    # env WRITES are not knob reads: chaos tooling arms faults in a
    # subprocess environment dict or os.environ freely
    os.environ["CCSC_FAULT_NAN_IT"] = "3"
    child_env = dict(os.environ)
    child_env["CCSC_FAULT_SIGTERM_IT"] = "5"
    return child_env


def non_ccsc_reads_are_out_of_scope():
    return os.environ.get("JAX_PLATFORMS")
