# known-clean fixture for the obs-schema check: declared events with
# their required fields, declared consumer reads


def emit_sites(run):
    run.event(
        "serve_request",
        replica_id=0,
        trace_id="74726163653031",
        bucket="4@64x64",
        latency_ms=1.5,
        iters=30,
        psnr=None,  # optional extras are free
    )
    run.event("fault_fired", fault="nan", iteration=3)


def passthrough(run, **fields):
    # **kwargs sites are not statically checkable for fields — the
    # event-name check still applies
    run.event("recovery", **fields)


def consumer(events):
    stalls = [e for e in events if e.get("type") == "stall"]
    by = {}
    for e in events:
        by.setdefault(e.get("type", "?"), []).append(e)
    return stalls, by.get("serve_dispatch", [])
