# known-clean fixture for the jit-purity check: idiomatic jitted code
# plus host-side code that uses host facilities legitimately
import time

import jax
import jax.numpy as jnp


@jax.jit
def hot_step(x):
    if jnp.iscomplexobj(x):  # static dtype predicate: fine
        x = jnp.abs(x)
    return jnp.sum(x * 2.0)


def host_driver(x):
    # NOT reachable from a jit boundary — host clocks are fine here
    t0 = time.perf_counter()
    y = hot_step(x)
    return y, time.perf_counter() - t0


def suppressed(x):
    t = time.time()  # ccsc: allow[jit-purity]
    return x + t


@jax.jit
def uses_suppressed(x):
    return suppressed(x)
