# known-bad fixture for the env-registry check
import os


def raw_reads():
    a = os.environ.get("CCSC_SOME_RAW_KNOB")  # L6: raw read
    b = os.environ["CCSC_RAW_SUBSCRIPT"]  # L7: raw subscript read
    return a, b


def aliased_read():
    import os as _os

    return _os.environ.get("CCSC_ALIASED_RAW")  # L14: aliased raw read


def undeclared_helper_read():
    from ccsc_code_iccv2017_tpu.utils import env

    return env.env_int("CCSC_NOT_IN_THE_REGISTRY")  # L20: undeclared
