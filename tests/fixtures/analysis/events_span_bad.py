# known-bad fixture for the obs-schema SPAN conventions: a span_end
# emitted for a literal span name that no span_start emitter anywhere
# in the project produces — an orphan by construction.


def emit_sites(run):
    run.event(  # L7: span_end for `orphan_phase` with no span_start
        "span_end",
        trace_id="t1",
        span="orphan_phase",
        span_id="s1",
        parent_span=None,
        replica_id=0,
        status="ok",
    )
