# known-clean fixture for the thread-safety check: consistent lock
# order, emits outside the lock, every thread joined
import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def ordered_one():
    with _lock_a:
        with _lock_b:
            pass


def ordered_two():
    with _lock_a:
        with _lock_b:
            pass


class Worker:
    def __init__(self, run):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._run = run
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()
        self._helpers = []
        t = threading.Thread(target=self._loop, daemon=True)
        self._helpers.append(t)
        t.start()

    def _loop(self):
        while True:
            with self._cv:
                self._cv.wait(timeout=0.1)  # releases the lock: fine
                n = 1
            # snapshot under the lock, emit OUTSIDE it
            self._run.event("serve_drain", replica_id=0, n=n)

    def close(self):
        self._worker.join(timeout=1.0)
        for t in self._helpers:
            t.join(timeout=1.0)
