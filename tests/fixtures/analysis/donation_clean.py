# known-clean fixture for the donation-safety check: the idiomatic
# rebind-at-the-call pattern of the chunked drivers
import jax


def make_step(f):
    return jax.jit(f, donate_argnums=(0,))


def good_driver(state, data, n):
    step = make_step(lambda s, d: (s, 0.0))
    for _ in range(n):
        # rebinding at the call statement: the old buffer dies inside
        # the call and the name now holds the fresh output
        state, aux = step(state, data)
    return state, aux


def good_rebind_then_read(state, data):
    step = make_step(lambda s, d: (s, 0.0))
    state, aux = step(state, data)
    return state.sum()  # reads the NEW binding — fine
