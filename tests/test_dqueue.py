"""Lease-protocol races of the durable file-lease work queue
(serve.dqueue) — the cross-host layer everything in
serve.federation stands on:

- concurrent claim of one item has exactly one winner (the atomic
  rename IS the lock);
- torn/truncated request, lease, and host-record files read as
  absent, never as errors;
- lease expiry is clock-skew-bounded: a heartbeat within
  ttl + skew is alive even when the clocks disagree, and only one
  older than that is reaped;
- reaper vs. late delivery fencing: a requeued lease's original
  owner is suppressed at complete time (lease gone / epoch stale /
  spent marker), the survivor's result stands, and epoch fencing
  refuses a previous incarnation of a rejoined host;
- the cross-host attempt budget rides the item record and
  exhaustion writes an explicit error result (exactly-once-or-
  error); spent keys stay spent — resubmission refused, requeued
  copies dropped at claim.

Pure filesystem tests: no engine, no backend, no fleet.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.serve.dqueue import DurableQueue, safe_key


def _x(seed=0, shape=(6, 6)):
    return (
        np.random.default_rng(seed)
        .random(shape)
        .astype(np.float32)
    )


def _q(tmp_path, host, **kw):
    ev = []
    kw.setdefault("ttl_s", 0.5)
    kw.setdefault("skew_s", 0.1)
    q = DurableQueue(
        str(tmp_path), host=host,
        emit=lambda t, **f: ev.append(dict(f, type=t)), **kw,
    )
    q.events = ev
    return q


def test_submit_claim_complete_roundtrip(tmp_path):
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    x = _x(1)
    client.submit("k1", x, mask=None, x_orig=x)
    items = a.claim(limit=4)
    assert len(items) == 1
    it = items[0]
    assert it["key"] == "k1" and it["attempts"] == 1
    assert np.array_equal(a.load_array(it["b"]), x)
    assert a.complete(
        it, x * 2, psnr=31.5, latency_ms=4.0, bucket="2@6x6", iters=3
    )
    res = client.result("k1")
    assert res["status"] == "ok"
    assert res["host"] == "A" and res["attempts"] == 1
    assert np.array_equal(client.load_array(res["recon"]), x * 2)
    # content digest pairs with the capture oracle's convention
    from ccsc_code_iccv2017_tpu.serve.capture import payload_sha

    assert res["digest"] == payload_sha(
        np.ascontiguousarray(np.asarray(x * 2))
    )
    assert client.spent("k1")
    st = client.stats()
    assert st["queued"] == 0 and st["leased"] == 0


def test_concurrent_claim_exactly_one_winner(tmp_path):
    client = _q(tmp_path, "client")
    hosts = [_q(tmp_path, f"H{i}") for i in range(4)]
    for h in hosts:
        h.join()
    client.submit("solo", _x(2))
    won = []
    barrier = threading.Barrier(len(hosts))

    def race(h):
        barrier.wait()
        won.extend(h.claim(limit=4))

    ts = [threading.Thread(target=race, args=(h,)) for h in hosts]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(won) == 1  # the rename has one winner, no lock file
    assert won[0]["key"] == "solo"


def test_torn_request_and_lease_files_read_as_absent(tmp_path):
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    # torn request file in queue/: claim skips (quarantines), never
    # raises, and a good item behind it is still claimed
    with open(tmp_path / "queue" / "000-torn.json", "w") as f:
        f.write('{"key": "tor')
    client.submit("good", _x(3))
    items = a.claim(limit=4)
    assert [i["key"] for i in items] == ["good"]
    assert not os.path.exists(tmp_path / "queue" / "000-torn.json")
    # torn lease file: stats and reap treat it as absent; after a
    # full TTL it is quarantined, not requeued as garbage
    lease = tmp_path / "leases" / "A" / "zzz-torn.json"
    with open(lease, "w") as f:
        f.write('{"key": "half')
    assert a.reap() == []  # young torn lease: left alone
    old = time.time() - 10.0
    os.utime(lease, (old, old))
    a.reap()
    assert not lease.exists()
    # torn host record reads as absent: expiry falls back to lease_t
    with open(tmp_path / "hosts" / "B.json", "w") as f:
        f.write('{"host": "B", "epo')
    assert "B" not in a._host_table()


def test_expiry_is_clock_skew_bounded(tmp_path):
    client = _q(tmp_path, "client", ttl_s=1.0, skew_s=0.5)
    a = _q(tmp_path, "A", ttl_s=1.0, skew_s=0.5)
    b = _q(tmp_path, "B", ttl_s=1.0, skew_s=0.5)
    a.join()
    b.join()
    client.submit("k", _x(4))
    assert a.claim()
    hb_path = a._host_path("A")

    def stamp(dt):
        rec = json.load(open(hb_path))
        rec["t"] = time.time() + dt
        with open(hb_path, "w") as f:
            json.dump(rec, f)

    # owner's clock running AHEAD of ours (skewed future heartbeat):
    # trivially alive, never reaped
    stamp(+3.0)
    assert b.reap() == []
    # heartbeat older than ttl but WITHIN the skew allowance: the
    # clocks may simply disagree — not death
    stamp(-1.2)
    assert b.reap() == []
    # older than ttl + skew: dead no matter whose clock is right
    stamp(-1.8)
    reaped = b.reap()
    assert [r["key"] for r in reaped] == ["k"]
    # the requeued item drains again, attempt count carried
    it = b.claim()[0]
    assert it["attempts"] == 2


def test_reaper_vs_late_delivery_fencing(tmp_path):
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A", ttl_s=0.2, skew_s=0.05)
    b = _q(tmp_path, "B", ttl_s=0.2, skew_s=0.05)
    a.join()
    b.join()
    x = _x(5)
    client.submit("k", x, trace_id="t1", root_span="r1")
    it_a = a.claim()[0]
    time.sleep(0.4)  # A's heartbeat goes stale (it is wedged)
    b.heartbeat()
    assert [r["key"] for r in b.reap()] == ["k"]
    it_b = b.claim()[0]
    assert b.complete(it_b, x * 3, latency_ms=1.0)
    # A wakes up and tries to deliver its stale ownership: fenced —
    # the spent marker + missing lease suppress it, B's result stands
    assert not a.complete(it_a, x * 3)
    res = client.result("k")
    assert res["host"] == "B" and res["attempts"] == 2
    sup = [e for e in a.events if e["type"] == "dqueue_suppressed"]
    assert sup and sup[-1]["key"] == "k"
    # the reaper wrote the dead ownership's span retrospectively, so
    # the trace still closes across the host boundary
    req_spans = [
        e for e in b.events
        if e["type"] in ("span_start", "span_end")
        and e.get("trace_id") == "t1"
    ]
    assert len(req_spans) == 2  # one retrospective start+end pair
    assert req_spans[-1]["status"] == "requeued"


def test_epoch_fencing_refuses_previous_incarnation(tmp_path):
    client = _q(tmp_path, "client")
    a1 = _q(tmp_path, "A")
    a1.join()
    client.submit("k", _x(6))
    it = a1.claim()[0]
    # the same host id rejoins (a supervisor restarted the process):
    # the NEW epoch fences the old incarnation even though the lease
    # file still exists and the heartbeat is fresh
    a2 = _q(tmp_path, "A")
    assert a2.join() == a1.epoch + 1
    assert a2.reap()  # epoch rule: old-epoch lease requeued at once
    assert not a1.complete(it, _x(6))  # stale epoch → suppressed


def test_attempt_budget_writes_explicit_error(tmp_path):
    client = _q(tmp_path, "client", max_attempts=2)
    client.submit("doomed", _x(7))
    b = _q(tmp_path, "B", ttl_s=0.1, skew_s=0.0)
    b.join()
    for _ in range(2):
        assert b.claim()
        time.sleep(0.25)
        # stale own heartbeat: reap from a fresh handle judges it
        r = _q(tmp_path, "R", ttl_s=0.1, skew_s=0.0)
        r.join()
        r.reap()
    res = client.result("doomed")
    assert res is not None and res["status"] == "error"
    assert res["attempts"] == 2
    assert client.spent("doomed")
    # exactly-once-OR-error: the spent key is refused forever
    with pytest.raises(ValueError):
        client.submit("doomed", _x(7))


def test_requeued_copy_of_spent_key_dropped_at_claim(tmp_path):
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    x = _x(8)
    client.submit("k", x)
    it = a.claim()[0]
    assert a.complete(it, x)
    # a stale requeued copy reappears (a racing reaper's rename that
    # lost the delivery race): claim drops it for free
    stale = dict(it)
    stale["attempts"] = 1
    with open(tmp_path / "queue" / it["name"], "w") as f:
        json.dump(stale, f)
    assert a.claim(limit=4) == []
    assert not os.path.exists(tmp_path / "queue" / it["name"])


def test_reaper_spares_unstamped_fresh_claim(tmp_path):
    """The claim window: the rename into the lease dir has landed but
    the ownership stamp has not. A reaper judging that record by its
    absent lease fields would read expired-since-epoch and steal a
    healthy host's fresh claim (then the claimer's stamp would
    recreate a ghost lease no reaper ever expires). The record must
    be judged by file age instead."""
    client = _q(tmp_path, "client", ttl_s=0.3, skew_s=0.1)
    a = _q(tmp_path, "A", ttl_s=0.3, skew_s=0.1)
    b = _q(tmp_path, "B", ttl_s=0.3, skew_s=0.1)
    a.join()
    b.join()
    name = client.submit("k", _x(12))
    # simulate mid-claim: rename only, no ownership stamp yet
    os.rename(
        tmp_path / "queue" / name, tmp_path / "leases" / "A" / name
    )
    assert b.reap() == []  # fresh unstamped claim: hands off
    st = client.stats()
    assert st["leased"] == 1 and st["queued"] == 0
    # the claimer died right there: after a full TTL the unstamped
    # lease is requeued, not leaked
    old = time.time() - 5.0
    os.utime(tmp_path / "leases" / "A" / name, (old, old))
    reaped = b.reap()
    assert [r["key"] for r in reaped] == ["k"]
    assert client.stats()["queued"] == 1


def test_result_record_is_first_wins(tmp_path):
    """A spent-race loser must never overwrite the winner's durable
    result with a contradictory record — the first published outcome
    is the client-visible one."""
    from ccsc_code_iccv2017_tpu.serve.dqueue import _publish_json

    p = str(tmp_path / "r.json")
    assert _publish_json(p, {"status": "ok", "who": "winner"})
    assert not _publish_json(p, {"status": "error", "who": "loser"})
    assert json.load(open(p))["who"] == "winner"
    # end-to-end: the reaper's budget-exhaustion error loses to a
    # delivery that already published
    client = _q(tmp_path, "client", max_attempts=1)
    a = _q(tmp_path, "A", ttl_s=0.1, skew_s=0.0)
    a.join()
    client.submit("k", _x(13))
    it = a.claim()[0]
    assert a.complete(it, _x(13) * 2)
    # a stale reaper view of the same exhausted item changes nothing
    assert not a._requeue(dict(it), str(tmp_path / "nope.json"), "x")
    assert client.result("k")["status"] == "ok"


def test_leave_releases_leases_and_seal_drained(tmp_path):
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    client.submit("k1", _x(9))
    client.submit("k2", _x(10))
    assert len(a.claim(limit=4)) == 2
    assert not client.drained
    assert a.leave() == 2  # orderly exit hands both back
    st = client.stats()
    assert st["queued"] == 2 and st["leased"] == 0
    assert st["hosts"]["A"]["status"] == "left"
    assert not client.sealed
    client.seal()
    assert client.sealed
    b = _q(tmp_path, "B")
    b.join()
    for it in b.claim(limit=4):
        assert b.complete(it, _x(11))
    assert client.drained
    # result/spent names are digest-safe for hostile keys
    assert "/" not in safe_key("../../etc/passwd")


# ------------------------------- request lifecycle (ISSUE 19)


def test_claim_of_expired_item_writes_durable_deadline_result(
    tmp_path,
):
    """The item record carries the ABSOLUTE deadline across hosts: a
    claim of an already-expired item never hands the payload out —
    it resolves the key durably (status='deadline' result + spent
    marker) so every frontend polling the queue sees the same
    terminal verdict, and a later resubmit of the key is refused."""
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    client.submit("late", _x(3), deadline=time.time() + 0.05)
    client.submit("fine", _x(4))
    time.sleep(0.1)  # 'late' is now past its budget
    items = a.claim(limit=4)
    assert [it["key"] for it in items] == ["fine"]
    res = client.result("late")
    assert res is not None and res["status"] == "deadline"
    assert client.spent("late")
    st = client.stats()
    assert st["queued"] == 0 and st["leased"] == 1
    assert any(
        e["type"] == "deadline_exceeded" and e.get("where") == "claim"
        for e in a.events
    )


def test_cancel_writes_durable_marker_and_claim_refuses(tmp_path):
    """Cooperative cancellation, cross-host: ``cancel`` publishes a
    durable status='cancelled' result FIRST (the first-wins result
    record is the decision point) and marks the key spent, so a
    later claim drops the item instead of solving it — and a cancel
    that lost the race to a real outcome reports False and leaves
    the outcome standing."""
    client = _q(tmp_path, "client")
    a = _q(tmp_path, "A")
    a.join()
    client.submit("bail", _x(5))
    assert client.cancel("bail") is True
    res = client.result("bail")
    assert res is not None and res["status"] == "cancelled"
    assert client.spent("bail")
    assert a.claim(limit=4) == []  # spent pre-claim: dropped
    # cancel after an outcome exists must NOT overwrite it
    client.submit("served", _x(6))
    (it,) = a.claim(limit=4)
    assert a.complete(it, _x(6) * 2)
    assert client.cancel("served") is False
    assert client.result("served")["status"] == "ok"
