"""Filter .mat round-trip and app driver smoke tests (tiny synthetic
configs; the apps are the reference's L5 drivers, SURVEY.md section 2.4)."""
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.utils import io_mat


@pytest.mark.parametrize(
    "shape,layout,loader",
    [
        ((6, 5, 5), "2d", io_mat.load_filters_2d),
        ((6, 4, 5, 5), "hyperspectral", io_mat.load_filters_hyperspectral),
        ((6, 5, 5, 5), "3d", io_mat.load_filters_3d),
        ((6, 3, 3, 5, 5), "lightfield", io_mat.load_filters_lightfield),
    ],
)
def test_filter_mat_roundtrip(tmp_path, shape, layout, loader):
    """save_filters writes the MATLAB reference layout; load_filters_*
    must restore our canonical [k, *reduce, *spatial] exactly."""
    r = np.random.default_rng(0)
    d = r.normal(size=shape).astype(np.float32)
    p = str(tmp_path / "f.mat")
    io_mat.save_filters(p, d, {"obj_vals_d": [1.0, 0.5]}, layout=layout)
    back = loader(p)
    np.testing.assert_allclose(back, d, rtol=1e-6)


@pytest.mark.parametrize(
    "dz_shape,layout",
    [
        ((3, 12, 12), "2d"),
        ((3, 4, 12, 12), "hyperspectral"),
        ((3, 12, 12, 6), "3d"),
        ((3, 2, 2, 12, 12), "lightfield"),
    ],
)
def test_dz_mat_roundtrip(tmp_path, dz_shape, layout):
    """The terminal save keeps Dz alongside d/iterations
    (learn_kernels_2D_large.m:45); the stored layout is the reference's
    data layout (spatial-first, n last) and round-trips exactly."""
    r = np.random.default_rng(3)
    nd = {"2d": (6, 5, 5), "hyperspectral": (6, 4, 5, 5),
          "3d": (6, 5, 5, 5), "lightfield": (6, 2, 2, 5, 5)}[layout]
    d = r.normal(size=nd).astype(np.float32)
    Dz = r.normal(size=dz_shape).astype(np.float32)
    p = str(tmp_path / "f.mat")
    io_mat.save_filters(p, d, {"obj_vals_d": [1.0]}, layout=layout, Dz=Dz)
    raw = io_mat._loadmat(p)
    assert "Dz" in raw and "d" in raw and "iterations" in raw
    # stored with n last, like the reference's b/Dz arrays
    assert raw["Dz"].shape[-1] == dz_shape[0]
    np.testing.assert_allclose(io_mat.load_dz(p, layout), Dz, rtol=1e-6)


def test_reference_layout_compat():
    """load_filters_2d on a MATLAB-layout array equals manual transpose."""
    import scipy.io, tempfile, os

    r = np.random.default_rng(1)
    mat = r.normal(size=(11, 11, 7)).astype(np.float32)  # MATLAB [s,s,k]
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "ref.mat")
        scipy.io.savemat(p, {"d": mat})
        ours = io_mat.load_filters_2d(p)
    assert ours.shape == (7, 11, 11)
    np.testing.assert_allclose(ours[3], mat[:, :, 3])


def test_synthetic_generators():
    from ccsc_code_iccv2017_tpu.data import volumes

    hs = volumes.synthetic_hyperspectral(n=2, bands=4, side=16)
    assert hs.shape == (2, 4, 16, 16) and np.isfinite(hs).all()
    vid = volumes.synthetic_video(n=2, side=12, frames=6)
    assert vid.shape == (2, 12, 12, 6) and np.isfinite(vid).all()
    lf = volumes.synthetic_lightfield(views=3, side=20)
    assert lf.shape == (3, 3, 20, 20) and np.isfinite(lf).all()
    patches = volumes.random_lightfield_patches(lf, 4, spatial=8)
    assert patches.shape == (4, 3, 3, 8, 8)
    crops = volumes.random_volume_crops(vid[0], 3, (6, 6, 4))
    assert crops.shape == (3, 6, 6, 4)


def test_learn_masked_rollback_and_convergence():
    """Masked learner (2-3D admm_learn.m rebuild): objective decreases
    and the rollback guard never lets it end worse than it started."""
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked
    from ccsc_code_iccv2017_tpu.data import volumes

    b = volumes.synthetic_hyperspectral(n=2, bands=4, side=20, seed=3)
    geom = ProblemGeom((5, 5), 6, (4,))
    cfg = LearnConfig(
        max_it=4, max_it_d=3, max_it_z=3, tol=1e-4, verbose="none"
    )
    res = learn_masked(jnp.asarray(b), geom, cfg)
    obj = res.trace["obj_vals_z"]
    assert len(obj) >= 1
    assert obj[-1] <= obj[0]
    assert res.d.shape == (6, 4, 5, 5)


def test_app_smoke_2d(tmp_path):
    """learn_2d -> inpaint_2d on the reference test images (tiny)."""
    import os

    if not os.path.isdir("/root/reference/2D/Inpainting/Test"):
        pytest.skip("reference not mounted")
    from ccsc_code_iccv2017_tpu.apps import inpaint_2d, learn_2d

    out = str(tmp_path / "f.mat")
    learn_2d.main(
        [
            "--data", "/root/reference/2D/Inpainting/Test",
            "--filters", "8", "--support", "5", "--blocks", "2",
            "--max-it", "2", "--size", "32", "--limit", "4",
            "--out", out, "--verbose", "none",
        ]
    )
    res = inpaint_2d.main(
        [
            "--data", "/root/reference/2D/Inpainting/Test",
            "--filters", out, "--limit", "1", "--size", "32",
            "--max-it", "5",
        ]
    )
    assert int(res.trace.num_iters) >= 1


def test_app_pipeline_hyperspectral(tmp_path):
    """learn_hyperspectral -> demosaic_hyperspectral, tiny synthetic."""
    from ccsc_code_iccv2017_tpu.apps import (
        demosaic_hyperspectral,
        learn_hyperspectral,
    )

    out = str(tmp_path / "hs.mat")
    learn_hyperspectral.main(
        [
            "--synthetic", "--bands", "4", "--filters", "4",
            "--support", "3", "--max-it", "1", "--limit", "2",
            "--out", out, "--verbose", "none",
        ]
    )
    res = demosaic_hyperspectral.main(
        ["--synthetic", "--filters", out, "--max-it", "4"]
    )
    assert int(res.trace.num_iters) >= 1


def test_app_pipeline_3d(tmp_path):
    """learn_3d -> deblur_video, tiny synthetic clips."""
    from ccsc_code_iccv2017_tpu.apps import deblur_video, learn_3d

    out = str(tmp_path / "f3d.mat")
    learn_3d.main(
        [
            "--synthetic", "--clips", "2", "--clip-size", "12",
            "--clip-frames", "6", "--filters", "4", "--support", "3",
            "--support-t", "3", "--blocks", "2", "--max-it", "1",
            "--out", out, "--verbose", "none",
        ]
    )
    res = deblur_video.main(
        [
            "--synthetic", "--filters", out, "--side", "16",
            "--frames", "6", "--max-it", "4",
        ]
    )
    assert int(res.trace.num_iters) >= 1


def test_app_pipeline_4d(tmp_path):
    """learn_4d -> view_synthesis, tiny synthetic lightfield."""
    from ccsc_code_iccv2017_tpu.apps import learn_4d, view_synthesis

    out = str(tmp_path / "f4d.mat")
    learn_4d.main(
        [
            "--synthetic", "--patches", "2", "--patch-size", "12",
            "--views", "3", "--filters", "4", "--support", "3",
            "--blocks", "2", "--max-it", "1", "--out", out,
            "--verbose", "none",
        ]
    )
    res = view_synthesis.main(
        [
            "--synthetic", "--filters", out, "--side", "16",
            "--max-it", "4",
        ]
    )
    assert int(res.trace.num_iters) >= 1


def test_app_pipeline_poisson(tmp_path):
    """learn_2d -> poisson_2d on reference images."""
    import os

    if not os.path.isdir("/root/reference/2D/Poisson_deconv/dataset_norm"):
        pytest.skip("reference not mounted")
    from ccsc_code_iccv2017_tpu.apps import learn_2d, poisson_2d

    out = str(tmp_path / "f.mat")
    learn_2d.main(
        [
            "--data", "/root/reference/2D/Poisson_deconv/dataset_norm",
            "--filters", "6", "--support", "5", "--blocks", "2",
            "--max-it", "1", "--size", "24", "--limit", "2",
            "--out", out, "--verbose", "none",
        ]
    )
    res = poisson_2d.main(
        [
            "--data", "/root/reference/2D/Poisson_deconv/dataset_norm",
            "--filters", out, "--limit", "1", "--size", "24",
            "--max-it", "4",
        ]
    )
    assert res is not None


def test_all_reference_artifacts_load():
    """Every shipped pretrained filter bank loads into the canonical
    [k, *reduce, *spatial] layout (SURVEY.md section 1, L1 assets)."""
    import os

    if not os.path.isdir("/root/reference"):
        pytest.skip("reference not mounted")
    cases = [
        ("/root/reference/2D/Filters/Filters_ours_2D_large.mat",
         io_mat.load_filters_2d, (100, 11, 11)),
        ("/root/reference/2-3D/Filters/2D-3D-Hyperspectral.mat",
         io_mat.load_filters_hyperspectral, (100, 31, 11, 11)),
        ("/root/reference/3D/Filters/3D_video_filters.mat",
         io_mat.load_filters_3d, (49, 11, 11, 11)),
        ("/root/reference/4D/Filters/4d_filters_lightfield.mat",
         io_mat.load_filters_lightfield, (49, 5, 5, 11, 11)),
    ]
    for path, loader, shape in cases:
        d = loader(path)
        assert d.shape == shape, (path, d.shape)
        assert np.isfinite(d).all(), path
        # trained banks are nontrivial: no dead (all-zero) filters
        flat = d.reshape(shape[0], -1)
        assert (np.abs(flat).max(axis=1) > 0).all(), path


def test_streaming_guard_names_cli_flags(tmp_path):
    # the shared dispatch guard must name the CLI flag as typed
    # (--profile-dir), not the Python kwarg (profile_dir).
    # --checkpoint-dir is no longer forbidden: the streaming learner
    # checkpoints natively (parallel.streaming).
    from ccsc_code_iccv2017_tpu.apps import learn_2d

    with pytest.raises(SystemExit, match="--profile-dir"):
        learn_2d.main(
            [
                "--data", "/root/reference/2D/Inpainting/Test",
                "--streaming", "--profile-dir", str(tmp_path / "prof"),
                "--filters", "4", "--support", "5",
                "--limit", "2", "--size", "16",
            ]
        )
