"""Chunked (lax.scan) outer driver + donated ADMM state
(LearnConfig.outer_chunk / donate_state):

- trajectory equality vs the per-step driver for the consensus AND
  masked learners (the chunk is an execution strategy, not a new
  algorithm), including partial chunks and mesh paths;
- donation metadata: every LearnState leaf is input-output aliased in
  the lowered executable, and the driver never touches a donated
  buffer;
- checkpoint/resume crossing chunk boundaries;
- tol early-stop landing on the same iterate at chunk granularity;
- the masked rollback carried inside the scan;
- streaming chunk-granular readback cadence.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
from ccsc_code_iccv2017_tpu.models.learn import learn
from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked
from ccsc_code_iccv2017_tpu.parallel import consensus


def _b2d(n=8, size=16, seed=0):
    r = np.random.default_rng(seed)
    return jnp.asarray(r.normal(size=(n, size, size)).astype(np.float32))


CFG = dict(
    max_it=6, max_it_d=3, max_it_z=3, num_blocks=2, rho_d=500.0,
    rho_z=10.0, lambda_prior=0.1, verbose="none", track_objective=True,
    tol=0.0,
)

TRACE_KEYS = ("obj_vals_d", "obj_vals_z", "d_diff", "z_diff")


def _assert_same_traj(ref, res, atol=1e-6, rtol=1e-6):
    np.testing.assert_allclose(
        np.asarray(ref.d), np.asarray(res.d), atol=atol
    )
    for k in TRACE_KEYS:
        np.testing.assert_allclose(
            ref.trace[k], res.trace[k], rtol=rtol, atol=atol,
            err_msg=k,
        )


@pytest.mark.parametrize(
    "chunk,donate", [(4, False), (4, True), (1, True), (3, False)]
)
def test_consensus_chunked_matches_per_step(chunk, donate):
    """outer_chunk folds N iterations into one dispatch; max_it=6 with
    chunk 4 exercises the partial final chunk. donate_state must not
    change a single trace value (pure buffer aliasing)."""
    b = _b2d()
    geom = ProblemGeom((5, 5), 6)
    ref = learn(b, geom, LearnConfig(**CFG), key=jax.random.PRNGKey(0))
    res = learn(
        b, geom,
        LearnConfig(**CFG, outer_chunk=chunk, donate_state=donate),
        key=jax.random.PRNGKey(0),
    )
    assert len(res.trace["obj_vals_z"]) == len(ref.trace["obj_vals_z"])
    _assert_same_traj(ref, res)


def test_chunked_matches_per_step_on_golden_fixture():
    """The acceptance fixture: outer_chunk=4 on the golden 2D problem
    (tests/test_golden.py seed/shape/config) equals the per-step driver
    to float tolerance — chunking is an execution strategy, not a
    behavioral change the golden strategy would need new values for."""
    r = np.random.default_rng(7)
    b = jnp.asarray(r.normal(size=(4, 16, 16)).astype(np.float32))
    geom = ProblemGeom((5, 5), 6)
    mk = lambda **e: LearnConfig(
        max_it=4, max_it_d=3, max_it_z=3, num_blocks=2,
        rho_d=500.0, rho_z=10.0, lambda_prior=0.5,
        verbose="none", track_objective=True, **e,
    )
    ref = learn(b, geom, mk(), key=jax.random.PRNGKey(42))
    res = learn(
        b, geom, mk(outer_chunk=4, donate_state=True),
        key=jax.random.PRNGKey(42),
    )
    _assert_same_traj(ref, res)


def test_consensus_chunked_matches_on_block_mesh():
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    b = _b2d()
    geom = ProblemGeom((5, 5), 6)
    ref = learn(b, geom, LearnConfig(**CFG), key=jax.random.PRNGKey(0))
    res = learn(
        b, geom, LearnConfig(**CFG, outer_chunk=3, donate_state=True),
        key=jax.random.PRNGKey(0), mesh=block_mesh(2),
    )
    np.testing.assert_allclose(
        np.asarray(ref.d), np.asarray(res.d), atol=2e-5
    )
    np.testing.assert_allclose(
        ref.trace["obj_vals_z"], res.trace["obj_vals_z"], rtol=1e-4
    )


@pytest.mark.parametrize("donate", [False, True])
def test_masked_chunked_matches_per_step(donate):
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 8, 8)).astype(np.float32))
    kw = dict(
        gamma_div_d=50.0, gamma_div_z=10.0, key=jax.random.PRNGKey(0)
    )
    mk = lambda **e: LearnConfig(
        max_it=5, max_it_d=2, max_it_z=2, verbose="none", tol=0.0,
        track_objective=True, **e,
    )
    ref = learn_masked(b, geom, mk(), **kw)
    res = learn_masked(
        b, geom, mk(outer_chunk=3, donate_state=donate), **kw
    )
    assert len(res.trace["obj_vals_z"]) == len(ref.trace["obj_vals_z"])
    _assert_same_traj(ref, res)


def test_donation_metadata_aliases_every_state_leaf():
    """With donate_state the compiled chunk step must alias EVERY
    LearnState leaf input->output (the acceptance criterion: assert on
    the executable's donation metadata, which exists on CPU too)."""
    b = _b2d()
    geom = ProblemGeom((5, 5), 6)
    cfg = LearnConfig(**CFG, outer_chunk=2, donate_state=True)
    fg = common.FreqGeom.create(geom, b.shape[-2:])
    state = learn_mod.init_state(
        jax.random.PRNGKey(0), geom, fg, 2, 4
    )
    b_blocks = jnp.asarray(np.asarray(b).reshape(2, 4, 16, 16))
    step = consensus.make_outer_chunk_step(
        geom, cfg, fg, 2, mesh=None, donate=True
    )
    lowered = step.lower(state, b_blocks)
    n_leaves = len(state)  # 6 LearnState arrays
    assert lowered.as_text().count("tf.aliasing_output") == n_leaves
    # and the HLO the executable actually carries records the aliasing
    compiled = lowered.compile()
    assert "input_output_alias" in compiled.as_text()


def test_donated_buffers_are_not_reused_by_driver():
    """After a donated call the old state buffers are dead (jax deletes
    them on CPU): the direct-call probe shows the deletion actually
    happens, and the learn() driver — which rebinds immediately — runs
    to completion with results identical to the undonated path."""
    b = _b2d()
    geom = ProblemGeom((5, 5), 6)
    cfg = LearnConfig(**CFG, outer_chunk=2, donate_state=True)
    fg = common.FreqGeom.create(geom, b.shape[-2:])
    state = learn_mod.init_state(jax.random.PRNGKey(0), geom, fg, 2, 4)
    b_blocks = jnp.asarray(np.asarray(b).reshape(2, 4, 16, 16))
    step = consensus.make_outer_chunk_step(
        geom, cfg, fg, 2, mesh=None, donate=True
    )
    new_state, _ = step(state, b_blocks)
    with pytest.raises(RuntimeError):
        np.asarray(state.z)  # donated away
    assert np.isfinite(np.asarray(new_state.z)).all()


def test_chunk_checkpoint_resume_mid_chunk(tmp_path):
    """A chunked run interrupted at an iteration that is NOT a chunk
    multiple of the resumed run must still reproduce the uninterrupted
    trajectory — the resume's first chunk is partial."""
    ck = str(tmp_path / "ck")
    b = _b2d(n=4, size=12, seed=1)
    geom = ProblemGeom((3, 3), 4)
    mk = lambda it, chunk: LearnConfig(
        max_it=it, max_it_d=2, max_it_z=2, num_blocks=2, rho_d=50.0,
        rho_z=2.0, tol=0.0, verbose="none", track_objective=True,
        outer_chunk=chunk, donate_state=True,
    )
    full = learn(b, geom, mk(7, 4), key=jax.random.PRNGKey(0))
    # interrupted after 3 iterations (chunks of 2: 2 + 1)
    learn(
        b, geom, mk(3, 2), key=jax.random.PRNGKey(0),
        checkpoint_dir=ck, checkpoint_every=2,
    )
    # resume with chunk 4 from start_it=3: first chunk covers 3..7
    resumed = learn(
        b, geom, mk(7, 4), key=jax.random.PRNGKey(0),
        checkpoint_dir=ck, checkpoint_every=2,
    )
    _assert_same_traj(full, resumed, atol=2e-5, rtol=1e-4)


def test_chunk_tol_early_stop_lands_on_same_iterate():
    """With a mid-trajectory tol both drivers must stop at the SAME
    iteration with the same final iterate: the chunked scan adopts the
    converged step (its trace entry counts) then freezes the carry."""
    b = _b2d()
    geom = ProblemGeom((5, 5), 6)
    probe = learn(
        b, geom, LearnConfig(**{**CFG, "max_it": 8}),
        key=jax.random.PRNGKey(0),
    )
    # a tol that triggers strictly inside the run: the per-iteration
    # max of both diffs, taken at 2/3 of the trajectory
    dd = np.maximum(
        np.asarray(probe.trace["d_diff"][1:]),
        np.asarray(probe.trace["z_diff"][1:]),
    )
    tol = float(dd[len(dd) * 2 // 3] * 1.000001)
    cfg_kw = {**CFG, "max_it": 8, "tol": tol}
    ref = learn(b, geom, LearnConfig(**cfg_kw), key=jax.random.PRNGKey(0))
    assert len(ref.trace["d_diff"]) < 9, "tol never triggered"
    res = learn(
        b, geom, LearnConfig(**cfg_kw, outer_chunk=3, donate_state=True),
        key=jax.random.PRNGKey(0),
    )
    assert len(res.trace["d_diff"]) == len(ref.trace["d_diff"])
    _assert_same_traj(ref, res)


def test_chunk_nan_guard_keeps_last_good_state(monkeypatch):
    """Divergence mid-chunk: the scan's last-finite-state carry must
    return the pre-divergence iterate (the per-step driver's contract
    at tests/test_learn.py::test_nan_guard_keeps_last_good_state).

    Poisoned via the sanctioned chaos point (CCSC_FAULT_NAN_IT inside
    the jitted step) — non-finite INPUT data is now rejected at the
    entry boundary by utils.validate, so it can no longer be used as a
    divergence trigger."""
    from ccsc_code_iccv2017_tpu.utils import faults

    geom = ProblemGeom((3, 3), 4)
    b = np.array(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    cfg = LearnConfig(
        max_it=4, max_it_d=1, max_it_z=1, num_blocks=2,
        rho_d=50.0, rho_z=2.0, verbose="none", track_objective=True,
        outer_chunk=2, donate_state=True,
    )
    monkeypatch.setenv("CCSC_FAULT_NAN_IT", "2")  # mid-first-chunk
    faults.reset()
    try:
        res = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0))
    finally:
        faults.reset()
    assert np.isfinite(np.asarray(res.d)).all()
    assert np.isfinite(np.asarray(res.z)).all()
    # the diverged iteration 2 was not adopted: obj0 + iteration 1 only
    assert len(res.trace["obj_vals_z"]) == 2
    assert all(np.isfinite(res.trace["obj_vals_z"]))


def test_masked_chunk_rollback_returns_prev_state():
    """The objective rollback carried inside the masked chunk scan:
    with obj_best already below any reachable objective, the first
    step must roll back — the scan returns the PREV iterate unchanged
    and flags the step rolled, exactly the per-step driver's
    state = prev; break."""
    from ccsc_code_iccv2017_tpu.models import learn_masked as lm
    from ccsc_code_iccv2017_tpu.ops import fourier

    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 8, 8)).astype(np.float32))
    cfg = LearnConfig(
        max_it=3, max_it_d=2, max_it_z=2, verbose="none", tol=0.0,
        track_objective=True, outer_chunk=3,
    )
    fg = common.FreqGeom.create(geom, (8, 8))
    radius = geom.psf_radius
    b_pad = fourier.pad_spatial(b, radius, target=fg.spatial_shape)
    M_pad = fourier.pad_spatial(
        jnp.ones_like(b), radius, target=fg.spatial_shape
    )
    sm = jnp.zeros_like(b_pad)
    kd, kz = jax.random.split(jax.random.PRNGKey(0))
    d0 = jax.random.normal(kd, (3, 3, 3), jnp.float32)
    d0 = jnp.broadcast_to(d0.reshape(3, 1, 3, 3), geom.filter_shape)
    d_full = fourier.circ_embed(d0, fg.spatial_shape)
    z0 = jax.random.normal(kz, (2, 3, *fg.spatial_shape), jnp.float32)
    x_shape = (2, 2, *fg.spatial_shape)
    state = lm.MaskedLearnState(
        d_full, jnp.zeros(x_shape), jnp.zeros_like(d_full),
        z0, jnp.zeros(x_shape), jnp.zeros_like(z0),
    )
    prev = jax.tree.map(lambda x: x + 1.0, state)  # distinguishable
    stepc = lm._chunk_step(geom, cfg, fg, 50.0, 10.0, 3, False, None)
    st, pv, best, ys = stepc(
        state, prev, jnp.float32(1e-30), b_pad, M_pad, sm
    )
    rolled = np.asarray(ys[6])
    active = np.asarray(ys[4])
    assert rolled[0] and not rolled[1:].any()
    assert active[0] and not active[1:].any()
    # rollback adopted prev (the reference's revert-both-iterates)
    for got, want in zip(st, prev):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_streaming_chunk_cadence_matches_per_step():
    from ccsc_code_iccv2017_tpu.parallel import streaming

    geom = ProblemGeom((3, 3), 4)
    cfg = LearnConfig(
        max_it=3, max_it_d=2, max_it_z=3, num_blocks=2, rho_d=50.0,
        rho_z=2.0, verbose="none", track_objective=True,
    )
    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    ref = streaming.learn_streaming(b, geom, cfg, key=jax.random.PRNGKey(0))
    for mode in ("device", "paged"):
        res = streaming.learn_streaming(
            b, geom, dataclasses.replace(cfg, outer_chunk=2),
            key=jax.random.PRNGKey(0), stream_mode=mode,
        )
        np.testing.assert_allclose(
            np.asarray(ref.d), np.asarray(res.d), atol=1e-6
        )
        np.testing.assert_allclose(
            ref.trace["obj_vals_z"], res.trace["obj_vals_z"], rtol=1e-6
        )


def test_streaming_chunk_tol_stop_trace_consistent_with_state():
    """Streaming has no last-good-state carry: a tol hit mid-chunk
    stops at the CHUNK boundary, and the trace covers every iteration
    the in-place state actually advanced through — the result equals a
    fixed-iteration run of that length."""
    from ccsc_code_iccv2017_tpu.parallel import streaming

    geom = ProblemGeom((3, 3), 4)
    base = LearnConfig(
        max_it=6, max_it_d=2, max_it_z=3, num_blocks=2, rho_d=50.0,
        rho_z=2.0, verbose="none", track_objective=True, tol=0.0,
    )
    b = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32
    )
    probe = streaming.learn_streaming(b, geom, base, key=jax.random.PRNGKey(0))
    dd = np.maximum(
        np.asarray(probe.trace["d_diff"][1:]),
        np.asarray(probe.trace["z_diff"][1:]),
    )
    # 0-based trigger index 2 (1-based iteration 3): mid-chunk for
    # chunk=2, and its boundary (4) is strictly before max_it
    k = 2
    tol = float(dd[k] * 1.000001)
    chunk = 2
    res = streaming.learn_streaming(
        b, geom, dataclasses.replace(base, tol=tol, outer_chunk=chunk),
        key=jax.random.PRNGKey(0),
    )
    n_done = len(res.trace["d_diff"]) - 1  # iterations actually run
    assert n_done < 6, "tol never triggered"
    assert n_done >= k + 1  # stopped at or after the per-step point
    assert n_done % chunk == 0  # ...on a chunk boundary
    # state is consistent with the trace: equals a fixed-length run
    ref = streaming.learn_streaming(
        b, geom, dataclasses.replace(base, max_it=n_done),
        key=jax.random.PRNGKey(0),
    )
    np.testing.assert_allclose(
        np.asarray(res.d), np.asarray(ref.d), atol=1e-6
    )
    np.testing.assert_allclose(
        res.trace["obj_vals_z"], ref.trace["obj_vals_z"], rtol=1e-6
    )


def test_streaming_rejects_donate_state():
    from ccsc_code_iccv2017_tpu.parallel import streaming

    b = np.zeros((2, 8, 8), np.float32)
    geom = ProblemGeom((3, 3), 2)
    cfg = LearnConfig(
        max_it=1, num_blocks=2, verbose="none", donate_state=True
    )
    with pytest.raises(ValueError, match="donate_state"):
        streaming.learn_streaming(b, geom, cfg)


def test_dispatch_stream_mode_requires_streaming():
    """--stream-mode without --streaming is an explicit error, not a
    silently-ignored env mutation (ADVICE r5)."""
    import os

    from ccsc_code_iccv2017_tpu.apps._dispatch import dispatch_learn

    b = np.zeros((2, 8, 8), np.float32)
    geom = ProblemGeom((3, 3), 2)
    cfg = LearnConfig(max_it=1, num_blocks=2, verbose="none")
    before = os.environ.get("CCSC_STREAM_MODE")
    with pytest.raises(SystemExit, match="stream-mode"):
        dispatch_learn(
            b, geom, cfg, jax.random.PRNGKey(0), None,
            streaming=False, stream_mode="device",
        )
    assert os.environ.get("CCSC_STREAM_MODE") == before


def test_perfmodel_donation_drops_state_output_copy():
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    kw = dict(
        num_blocks=2, ni=4, k=8, spatial=(24, 24), num_freq=24 * 13,
        max_it_d=3, max_it_z=5,
    )
    base = perfmodel.analytic_outer_step_cost(**kw)
    don = perfmodel.analytic_outer_step_cost(**kw, donate_state=True)
    assert don["flops"] == base["flops"]
    assert don["bytes"] < base["bytes"]
    # the delta is exactly one read+write of the full ADMM state
    S = 24 * 24
    state = (2 * 2 * 4 * 8 + 2 * 2 * 8 + 2 * 8) * S * 4
    assert base["bytes"] - don["bytes"] == pytest.approx(2 * state)


def test_outer_chunk_validated_at_construction():
    """An invalid outer_chunk fails when the config is BUILT — the same
    error on every learner path (streaming never reads chunked_driver,
    so a property-time check would let it slip through there)."""
    with pytest.raises(ValueError, match="outer_chunk"):
        LearnConfig(outer_chunk=0)


def test_newton_iters_env_resolution(monkeypatch):
    from ccsc_code_iccv2017_tpu.ops import freq_solvers

    monkeypatch.delenv("CCSC_HERM_INV_ITERS", raising=False)
    assert freq_solvers.resolve_newton_iters() == 30
    assert freq_solvers.resolve_newton_iters(7) == 7
    monkeypatch.setenv("CCSC_HERM_INV_ITERS", "42")
    assert freq_solvers.resolve_newton_iters() == 42
    assert freq_solvers.resolve_newton_iters(7) == 7
