"""The fault-tolerant serving fleet (serve.ServeFleet): replicated
engines behind one durable queue, health-driven requeue, admission
control with a predictable overload ladder.

Contracts under test (ISSUE 7):
- CHAOS PARITY: with replicas and injected kill + hang faults
  mid-stream, every non-faulted request completes with a result
  bit-identical to a single unfaulted engine's serve of the same
  request, zero requests are lost or served twice, and the restarted
  casualty rejoins and serves — all asserted from the obs stream;
- requeue idempotency: a request handed off mid-dispatch is served
  exactly once; a recovered straggler's late result is suppressed
  (at-most-once delivery);
- admission control: beyond the queue ceiling submit raises an
  explicit ``Overloaded`` with a retry-after hint — queue depth is
  BOUNDED, never silent growth toward OOM — and admitted requests
  finish with bounded latency;
- the overload ladder walks shed-batching -> reject -> degrade and
  back, each transition an obs event, rung 3 recycling replicas onto
  a reduced solve budget;
- every serve_*/fleet_* obs record carries a ``replica_id`` field
  (runtime assertion here + source lint below).
"""
import os
import time
from concurrent.futures import Future

import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import (
    FleetConfig,
    ProblemGeom,
    ServeConfig,
    SolveConfig,
)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
)
from ccsc_code_iccv2017_tpu.serve import (
    CodecEngine,
    Overloaded,
    ServeFleet,
)
from ccsc_code_iccv2017_tpu.serve.fleet import _FleetRequest
from ccsc_code_iccv2017_tpu.utils import faults, obs
from ccsc_code_iccv2017_tpu.utils.validate import CCSCInputError


@pytest.fixture(autouse=True)
def _fault_isolation(monkeypatch):
    for v in (
        "CCSC_FAULT_ENGINE_KILL_REQ",
        "CCSC_FAULT_ENGINE_KILL_REPLICA",
        "CCSC_FAULT_ENGINE_HANG_REQ",
        "CCSC_FAULT_ENGINE_HANG_REPLICA",
        "CCSC_FAULT_ENGINE_HANG_S",
        "CCSC_FAULT_ENGINE_SLOW_REQ",
        "CCSC_FAULT_ENGINE_SLOW_REPLICA",
        "CCSC_FAULT_ENGINE_SLOW_S",
        "CCSC_REQ_DEADLINE_MS",
        "CCSC_HEDGE_AFTER_MS",
        "CCSC_FAULT_STATE_DIR",
        "CCSC_WATCHDOG_ACTION",
        "CCSC_WATCHDOG_MIN_S",
        "CCSC_WATCHDOG_COMPILE_S",
    ):
        monkeypatch.delenv(v, raising=False)
    faults.reset()
    yield
    faults.reset()


def _bank(k=4, s=3, seed=0):
    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return jnp.asarray(d)


def _cfg(**kw):
    base = dict(
        lambda_residual=5.0, lambda_prior=0.3, max_it=4, tol=0.0,
        verbose="none", track_objective=True,
    )
    base.update(kw)
    return SolveConfig(**base)


def _reqs(n, side=12, seed=1):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = r.random((side, side)).astype(np.float32)
        m = (r.random((side, side)) < 0.5).astype(np.float32)
        out.append((x, m))
    return out


def _fleet(d, cfg, tmp_path=None, *, buckets=((2, (12, 12)),), **kw):
    scfg = ServeConfig(
        buckets=buckets, max_wait_ms=kw.pop("max_wait_ms", 2.0),
        verbose="none",
    )
    fkw = dict(
        min_queue_depth=64, restart_backoff_s=0.05,
        heartbeat_s=0.2, health_interval_s=0.05, verbose="none",
        metrics_dir=str(tmp_path) if tmp_path is not None else None,
    )
    fkw.update(kw)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    return ServeFleet(
        d, ReconstructionProblem(geom), cfg, scfg, FleetConfig(**fkw)
    )


def _single_engine_results(d, cfg, reqs, buckets=((2, (12, 12)),)):
    """The parity reference: one unfaulted CodecEngine, same pinned
    (bank, problem, SolveConfig, buckets)."""
    scfg = ServeConfig(buckets=buckets, max_wait_ms=2.0, verbose="none")
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    eng = CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)
    try:
        futs = [eng.submit(x * m, mask=m) for x, m in reqs]
        return [f.result(timeout=180) for f in futs]
    finally:
        eng.close()


# ------------------------------------------------------------- basics


def test_fleet_single_replica_bit_identical_no_faults():
    d = _bank()
    cfg = _cfg()
    reqs = _reqs(4)
    ref = _single_engine_results(d, cfg, reqs)
    fleet = _fleet(d, cfg, replicas=1)
    try:
        futs = [fleet.submit(x * m, mask=m) for x, m in reqs]
        res = [f.result(timeout=180) for f in futs]
    finally:
        fleet.close()
    for i in range(len(reqs)):
        np.testing.assert_array_equal(res[i].recon, ref[i].recon)
        assert int(res[i].trace.num_iters) == int(
            ref[i].trace.num_iters
        )


def test_idempotency_key_api():
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1, max_wait_ms=500.0)
    try:
        x, m = _reqs(1)[0]
        f1 = fleet.submit(x * m, mask=m, key="dup")
        f2 = fleet.submit(x * m, mask=m, key="dup")
        assert f1 is f2  # still in flight: the SAME future
        res = f1.result(timeout=120)
        assert res.recon.shape == (12, 12)
        # wait until delivery bookkeeping has settled
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                fleet.submit(x * m, mask=m, key="dup")
            except CCSCInputError as e:
                assert "already served" in str(e)
                break
            time.sleep(0.02)
        else:
            pytest.fail("resubmitting a served key was not refused")
    finally:
        fleet.close()


def test_fleet_close_reentrant_and_submit_after_close():
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1)
    x, m = _reqs(1)[0]
    fleet.reconstruct(x * m, mask=m)
    assert not fleet.closed
    fleet.close()
    assert fleet.closed
    fleet.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(x * m, mask=m)


def test_requeue_max_attempts_exhausted_errors():
    """The exactly-once-OR-ERROR half of the delivery contract: a
    request whose ownership budget is spent gets an explicit error on
    requeue, never a silent retry-forever."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1, max_attempts=2)
    try:
        rep = fleet._replicas[0]
        req = _FleetRequest(
            key="doomed", b=np.zeros((12, 12), np.float32), mask=None,
            smooth_init=None, x_orig=None, future=Future(),
            t_submit=time.perf_counter(), attempts=2,
        )
        with fleet._cv:
            fleet._index["doomed"] = req
            rep.assigned.append(req)
        fleet._requeue_from(rep, reason="test")
        with pytest.raises(RuntimeError, match="delivery attempts"):
            req.future.result(timeout=5)
        assert fleet.stats()["n_failed"] == 1
    finally:
        fleet.close()


def test_failed_key_is_spent_and_late_result_suppressed():
    """Exactly-once-OR-error means OR: once a key's future carries the
    max_attempts error, a recovered straggler's late result for it is
    suppressed (not recorded as a served request) and resubmitting the
    key is refused — the client can never see both an error and a
    result for one key."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1, max_attempts=1)
    try:
        x, m = _reqs(1)[0]
        res = fleet.reconstruct(x * m, mask=m, timeout=120)
        rep = fleet._replicas[0]
        req = _FleetRequest(
            key="doomed", b=x * m, mask=m, smooth_init=None,
            x_orig=None, future=Future(),
            t_submit=time.perf_counter(), attempts=1,
        )
        with fleet._cv:
            fleet._index["doomed"] = req
            rep.assigned.append(req)
        fleet._requeue_from(rep, reason="test")
        with pytest.raises(RuntimeError, match="delivery attempts"):
            req.future.result(timeout=5)
        n_before = fleet.stats()["n_requests"]
        served_before = rep.served
        # the straggler wakes with a late result for the failed key
        fleet._deliver(rep, req, res)
        st = fleet.stats()
        assert st["n_requests"] == n_before  # not recorded as served
        assert rep.served == served_before
        assert st["n_duplicates_suppressed"] == 1
        with pytest.raises(RuntimeError, match="delivery attempts"):
            req.future.result(timeout=0)  # error stands, no result
        with pytest.raises(CCSCInputError, match="already failed"):
            fleet.submit(x * m, mask=m, key="doomed")
    finally:
        fleet.close()


def test_take_drops_requeued_copy_of_resolved_key():
    """A requeued copy of a key a straggler already delivered must be
    dropped inside _take — running the full solve only to have the
    delivery suppressed would waste a dispatch."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1, max_wait_ms=2.0)
    try:
        x, m = _reqs(1)[0]
        fleet.reconstruct(x * m, mask=m, key="k1", timeout=120)
        ghost = _FleetRequest(
            key="k1", b=x * m, mask=m, smooth_init=None, x_orig=None,
            future=Future(), t_submit=time.perf_counter(), attempts=1,
        )
        with fleet._cv:
            fleet._index["k1"] = ghost
            fleet._queue.append(ghost)
            fleet._cv.notify_all()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with fleet._cv:
                if not fleet._queue and "k1" not in fleet._index:
                    break
            time.sleep(0.02)
        else:
            pytest.fail("requeued copy of a delivered key not dropped")
        st = fleet.stats()
        assert st["n_requests"] == 1  # the real delivery only
        # dropped BEFORE the solve: nothing reached _deliver to be
        # suppressed there
        assert st["n_duplicates_suppressed"] == 0
        assert not ghost.future.done()
    finally:
        fleet.close()


def test_transient_all_retired_does_not_fail_queue():
    """Replica 0 is abandoned (budget exhausted) while replica 1 sits
    in restart backoff: the queue must survive — only when EVERY
    replica is abandoned do pending futures get the no-capacity
    error."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=2)
    try:
        req = _FleetRequest(
            key="pending", b=np.zeros((12, 12), np.float32), mask=None,
            smooth_init=None, x_orig=None, future=Future(),
            t_submit=time.perf_counter(),
        )
        with fleet._cv:
            for rep in fleet._replicas:
                rep.retired = True  # both transiently down
            fleet._abandoned.add(0)  # only replica 0 is terminal
            fleet._index["pending"] = req
            fleet._queue.append(req)
            fleet._fail_if_no_capacity()
            assert len(fleet._queue) == 1  # replica 1 is coming back
            assert not req.future.done()
            fleet._abandoned.add(1)  # now nobody is coming back
            fleet._fail_if_no_capacity()
            assert not fleet._queue
        with pytest.raises(RuntimeError, match="no live replicas"):
            req.future.result(timeout=5)
        # and the door is closed: a fresh submit is refused up front
        # instead of returning a future no worker will ever take
        x, m = _reqs(1)[0]
        with pytest.raises(RuntimeError, match="no live replicas"):
            fleet.submit(x * m, mask=m)
    finally:
        with fleet._cv:  # let close() retire them cleanly
            for rep in fleet._replicas:
                rep.retired = False
        fleet.close()


def test_replica_death_drains_engine_queue():
    """The crash path hands the casualty's engine-queued work back via
    drain_pending (the documented handoff hook) before closing it, so
    close() never spends a dispatch on results nobody will read."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1)
    try:
        rep = fleet._replicas[0]
        calls = []
        orig = rep.engine.drain_pending
        rep.engine.drain_pending = lambda: calls.append(1) or orig()
        fleet._on_replica_death(rep, RuntimeError("injected"))
        assert calls, "death path did not drain the engine queue"
        # the replacement rejoins and serves
        x, m = _reqs(1)[0]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with fleet._cv:
                live = not fleet._replicas[0].retired
            if live:
                break
            time.sleep(0.05)
        res = fleet.reconstruct(x * m, mask=m, timeout=120)
        assert res.recon.shape == (12, 12)
    finally:
        fleet.close()


def test_delivery_bookkeeping_is_bounded():
    """A long-lived fleet must not grow per-request state forever: the
    served/failed key stores are capped at FleetConfig.key_window
    (newest win) and the latency sample at latency_window, while the
    delivered COUNT keeps counting — the admission control that
    prevents queue OOM must not be undermined by the bookkeeping."""
    d = _bank()
    fleet = _fleet(
        d, _cfg(), replicas=1, key_window=4, latency_window=3,
    )
    try:
        for i, (x, m) in enumerate(_reqs(8, seed=11)):
            fleet.reconstruct(x * m, mask=m, key=f"b{i}", timeout=120)
        st = fleet.stats()
        assert st["n_requests"] == 8  # the count never truncates
        assert len(fleet._delivered) == 4  # the keys do
        assert len(fleet._latencies) == 3
        # the newest keys are the ones remembered
        assert list(fleet._delivered) == [f"b{i}" for i in range(4, 8)]
        # inside the window the idempotency refusal still holds
        x, m = _reqs(1)[0]
        with pytest.raises(CCSCInputError, match="already served"):
            fleet.submit(x * m, mask=m, key="b7")
    finally:
        fleet.close()


def test_derived_ceiling_credits_degraded_budget():
    """Rung 3 recycles replicas onto max_it x degrade_max_it_factor,
    which raises real request throughput — serving_bound must be
    computed with the EFFECTIVE budget, or the admission ceiling and
    retry-after undersell exactly the capacity the degrade bought."""
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    d = _bank()
    fleet = _fleet(
        d, _cfg(max_it=8), replicas=1, max_queue_depth=10,
        degrade_max_it_factor=0.5,
        health_interval_s=30.0,  # keep the monitor out of the way
    )
    try:
        rep = fleet._replicas[0]
        rep.engine._last_it_rate = 100.0  # a measured dispatch rate
        fleet._update_ceiling(perfmodel, [rep])
        rps_full = fleet._bound_rps
        assert rps_full > 0
        fleet._degraded = True
        fleet._update_ceiling(perfmodel, [rep])
        assert fleet._bound_rps == pytest.approx(2.0 * rps_full)
    finally:
        fleet._degraded = False
        fleet.close()


def test_constructor_failure_stops_spawned_watchdogs(monkeypatch):
    """ServeFleet.__init__'s failure path must release EVERYTHING the
    replicas it did manage to spawn acquired — not just their engines.
    A supervisor that retries fleet construction in a loop would
    otherwise accumulate one ccsc-watchdog poll thread per spawned
    replica per failed attempt for the life of the process."""
    import threading

    from ccsc_code_iccv2017_tpu.serve import fleet as fleet_mod

    def _dogs():
        return sum(
            t.name == "ccsc-watchdog" and t.is_alive()
            for t in threading.enumerate()
        )

    before = _dogs()
    real_engine = fleet_mod.CodecEngine
    calls = {"n": 0}

    def flaky_engine(*a, **kw):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise RuntimeError("boom: replica 1 failed to build")
        return real_engine(*a, **kw)

    monkeypatch.setattr(fleet_mod, "CodecEngine", flaky_engine)
    with pytest.raises(RuntimeError, match="replica 1 failed"):
        _fleet(_bank(), _cfg(), replicas=2)
    assert calls["n"] == 2
    # watchdog.stop() joins (2s); poll briefly for the quiet exit
    deadline = time.monotonic() + 5.0
    while _dogs() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert _dogs() == before


def test_recycle_thread_is_joined_by_close():
    """The rung-3 recycle walker is a TRACKED thread (lint:
    thread-safety): close() joins it, so an interpreter exit can never
    catch it alive mid-work — the PR 7 leaked-thread abort class."""
    import threading

    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1)
    try:
        fleet._start_recycle()
        assert fleet._recycle_thread is not None
    finally:
        fleet.close()
    assert not fleet._recycle_thread.is_alive()
    assert not any(
        t.name == "ccsc-fleet-recycle" and t.is_alive()
        for t in threading.enumerate()
    )


def test_malformed_hang_env_never_crashes(monkeypatch):
    """The chaos knobs keep the module's never-crash stance: a typo'd
    CCSC_FAULT_ENGINE_HANG_S must not raise from inside the replica
    worker (where it would be booked as a replica crash and burn
    restart budget on every restarted generation)."""
    monkeypatch.setenv("CCSC_FAULT_ENGINE_HANG_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_HANG_S", "10s")
    faults.reset()
    dur = faults.engine_hang_request(0, 1)
    assert dur == 3600.0  # the wedged-forever default, not a raise


def _recycling_with_inflight(fleet, key="inflight"):
    """Put replica 0 in the state the rung-3 recycle loop leaves it in
    — retired, state='recycling', handoff NOT yet performed — with one
    request still in flight on it."""
    x, m = _reqs(1)[0]
    rep = fleet._replicas[0]
    req = _FleetRequest(
        key=key, b=x * m, mask=m, smooth_init=None, x_orig=None,
        future=Future(), t_submit=time.perf_counter(), attempts=1,
    )
    with fleet._cv:
        rep.retired = True
        rep.state = "recycling"
        fleet._index[key] = req
        rep.assigned.append(req)
    return rep, req


def test_recycling_replica_crash_still_hands_off():
    """A replica retired for a rung-3 recycle that CRASHES mid-dispatch
    (before its clean recycle exit) still owes its casualty handoff:
    its in-flight requests must be requeued onto the replacement and
    the slot respawned. Regression — the death handler used to treat
    any ``retired`` replica as already drained, leaving the requests'
    futures hanging forever and the slot a dead husk (``reaped``, not
    ``retired``, gates the handoff)."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1)
    try:
        rep, req = _recycling_with_inflight(fleet)
        # the worker crashes before the clean recycle exit could run
        fleet._on_replica_death(rep, RuntimeError("injected"))
        assert rep.reaped
        # the request was requeued, the replacement spawns and serves
        # it — the future resolves instead of hanging until close
        res = req.future.result(timeout=180)
        assert res.recon.shape == (12, 12)
        cur = fleet._replicas[0]
        assert cur.generation == rep.generation + 1
        assert fleet.stats()["n_requeued"] == 1
    finally:
        fleet.close()


def test_recycling_replica_stall_still_hands_off():
    """Same hole via the stall path: a wedged recycling worker fires
    the watchdog — the stall handler must not early-return on
    ``retired`` but drain and respawn like any other casualty."""
    d = _bank()
    fleet = _fleet(d, _cfg(), replicas=1)
    try:
        rep, req = _recycling_with_inflight(fleet, key="stalled")
        fleet._on_replica_stall(rep, "replica0-dispatch")
        assert rep.reaped
        res = req.future.result(timeout=180)
        assert res.recon.shape == (12, 12)
        assert fleet._replicas[0].generation == rep.generation + 1
    finally:
        fleet.close()


# ------------------------------------------------------- chaos parity


def test_chaos_kill_hang_zero_lost_bit_identical(tmp_path, monkeypatch):
    """The ISSUE 7 acceptance chaos test: 3 replicas, replica 0 killed
    and replica 1 hung mid-stream. Every request completes exactly
    once, bit-identical to a single unfaulted engine; the hung
    straggler's late deliveries are suppressed; both casualties
    restart, rejoin, and serve — all read back from the obs stream."""
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REQ", "2")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REPLICA", "0")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_HANG_REQ", "2")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_HANG_REPLICA", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_HANG_S", "2.5")
    monkeypatch.setenv("CCSC_WATCHDOG_MIN_S", "0.4")
    monkeypatch.setenv("CCSC_WATCHDOG_COMPILE_S", "0.4")
    faults.reset()
    d = _bank()
    cfg = _cfg()
    reqs = _reqs(12)
    ref = _single_engine_results(d, cfg, reqs)

    fleet = _fleet(d, cfg, tmp_path, replicas=3)
    try:
        futs = [
            fleet.submit(x * m, mask=m, key=f"k{i}")
            for i, (x, m) in enumerate(reqs)
        ]
        res = [f.result(timeout=300) for f in futs]

        # zero lost: every future resolved with a real result,
        # bit-identical to the unfaulted single-engine serve
        assert len(res) == 12
        for i in range(12):
            np.testing.assert_array_equal(res[i].recon, ref[i].recon)
            assert int(res[i].trace.num_iters) == int(
                ref[i].trace.num_iters
            )

        # the casualties rejoin: wait for 3 live replicas again
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = fleet.stats()
            live = [
                r for r in st["replicas"]
                if r is not None and r["state"] == "live"
            ]
            if len(live) == 3:
                break
            time.sleep(0.05)
        assert len(live) == 3, st["replicas"]
        restarted = {
            r["replica"] for r in st["replicas"]
            if r is not None and r["generation"] > 0
        }
        assert restarted == {0, 1}

        # ... and SERVE: keep offering fresh work until a restarted
        # replica delivers (replicas race for the queue, so one wave
        # may be won entirely by the incumbent)
        served_by_restarted = False
        for wave in range(10):
            wf = [
                fleet.submit(x * m, mask=m, key=f"w{wave}-{i}")
                for i, (x, m) in enumerate(_reqs(6, seed=50 + wave))
            ]
            [f.result(timeout=120) for f in wf]
            ev = obs.read_events(str(tmp_path))
            ready_t = {
                e["replica_id"]: e["t"]
                for e in ev if e["type"] == "fleet_replica_ready"
            }
            if any(
                e["type"] == "fleet_request"
                and e["replica_id"] in restarted
                and e["t"] > ready_t.get(e["replica_id"], np.inf)
                for e in ev
            ):
                served_by_restarted = True
                break
        assert served_by_restarted

        # the hung straggler wakes 2.5 s after its take and delivers
        # late — wait for the suppression to land BEFORE closing (an
        # abandoned worker is deliberately not joined by close())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            ev = obs.read_events(str(tmp_path))
            if any(
                e["type"] == "fleet_duplicate_suppressed" for e in ev
            ):
                break
            time.sleep(0.1)
    finally:
        fleet.close()
    # the exact host-measured latency sample (seconds), for the
    # histogram-accuracy acceptance below
    host_latencies = list(fleet._latencies)

    events = obs.read_events(str(tmp_path), recursive=True)
    # every serve_*/fleet_*/span_* record names its replica (None
    # allowed only for fleet-scope records) — the runtime half of
    # the lint
    for e in events:
        t = e.get("type", "")
        if (
            t.startswith("serve_") or t.startswith("fleet_")
            or t.startswith("span_")
        ):
            assert "replica_id" in e, e

    dead = [e for e in events if e["type"] == "fleet_replica_dead"]
    reasons = {e["replica_id"]: e["reason"] for e in dead}
    assert reasons[0] == "crash" and reasons[1] == "stall"
    stalls = [e for e in events if e["type"] == "stall"]
    assert any(e.get("replica_id") == 1 for e in stalls)
    assert [e for e in events if e["type"] == "fleet_requeue"]
    # exactly-once delivery of the original 12 keys
    first_wave = [
        e for e in events
        if e["type"] == "fleet_request" and e["key"].startswith("k")
    ]
    keys = [e["key"] for e in first_wave]
    assert sorted(keys) == sorted(f"k{i}" for i in range(12))
    assert len(keys) == len(set(keys)), "a request was served twice"
    # some were handed off (attempts > 1)
    assert any(e["attempts"] > 1 for e in first_wave)
    # the hung straggler woke after 2.5 s and its late results for
    # already-delivered keys were suppressed (at-most-once)
    assert [
        e for e in events if e["type"] == "fleet_duplicate_suppressed"
    ]
    # the fleet closed with nothing lost
    summary = [
        e for e in events
        if e["type"] == "summary" and e.get("n_requeued") is not None
    ][-1]
    assert summary["n_failed"] == 0

    # ISSUE 9 acceptance (a): from the event streams ALONE, every
    # submitted trace_id reassembles into a complete, gap-free span
    # tree — including the requests requeued across the replica kill
    # and the hang (their story shows both ownerships)
    from ccsc_code_iccv2017_tpu.utils import trace as trace_util

    traces = trace_util.assemble(events)
    tid_by_key = {
        e["key"]: e["trace_id"]
        for e in events
        if e["type"] == "fleet_request"
    }
    for i in range(12):
        tid = tid_by_key[f"k{i}"]
        tr = traces[tid]
        assert tr.complete, (
            f"k{i}",
            [
                (s.name, s.status, s.closed)
                for s in tr.spans.values()
            ],
        )
    orphans = sum(
        len(t.orphans) + len(t.unparented) for t in traces.values()
    )
    assert orphans == 0, "span trees must reassemble gap-free"
    requeued_keys = [
        e["key"] for e in first_wave if e["attempts"] > 1
    ]
    tr = traces[tid_by_key[requeued_keys[0]]]
    attempts = tr.by_name("attempt")
    assert len(attempts) >= 2, "the handoff must be visible as spans"
    assert any(s.status == "requeued" for s in attempts)
    assert any(s.status == "ok" for s in attempts)
    # the fleet queue span was re-opened for the second ownership
    assert len(tr.by_name("queue")) >= 2

    # ISSUE 9 acceptance (b): fleet-wide percentiles recomputed from
    # the LAST slo_histogram event match the host-measured exact
    # sample within one bucket width
    from ccsc_code_iccv2017_tpu.serve import slo as slo_mod

    fleet_hists = [
        e for e in events
        if e["type"] == "slo_histogram"
        and e.get("replica_id") is None
        and e.get("phase") == "total"
    ]
    assert fleet_hists, "the fleet must flush its histogram at close"
    hist = slo_mod.from_snapshot(fleet_hists[-1])
    exact_ms = sorted(v * 1e3 for v in host_latencies)
    assert hist.n == len(exact_ms)
    for q in (0.50, 0.95, 0.99):
        ex = obs.percentile(exact_ms, q)
        got = hist.percentile(q)
        assert abs(got - ex) <= hist.bucket_width_ms(ex) + 1e-6, (
            q, got, ex,
        )


# -------------------------------------------------- admission control


def test_overload_explicit_ceiling_rejects_and_bounds_queue(tmp_path):
    d = _bank()
    fleet = _fleet(
        d, _cfg(max_it=30), tmp_path, replicas=1,
        buckets=((1, (12, 12)),), max_wait_ms=0.0,
        max_queue_depth=4,
    )
    admitted, rejected = [], 0
    retry_hints = []
    try:
        for i, (x, m) in enumerate(_reqs(16)):
            try:
                admitted.append(fleet.submit(x * m, mask=m, key=f"o{i}"))
            except Overloaded as e:
                rejected += 1
                retry_hints.append(e.retry_after_s)
        results = [f.result(timeout=300) for f in admitted]
        st = fleet.stats()
    finally:
        fleet.close()
    # explicit rejections, not silent queue growth
    assert rejected >= 1
    assert all(h > 0 for h in retry_hints)
    assert st["n_rejected"] == rejected
    # every ADMITTED request completed, with a real latency summary
    assert len(results) == len(admitted)
    assert st["p99_latency_s"] is not None
    events = obs.read_events(str(tmp_path))
    rej = [e for e in events if e["type"] == "fleet_admission_reject"]
    assert len(rej) == rejected
    # the queue never grew past its ceiling
    assert all(e["queue_depth"] <= 4 for e in rej)


def test_overload_derived_ceiling_from_serving_bound(tmp_path):
    """The acceptance overload test against the DERIVED ceiling: after
    a dispatch has measured an iteration rate, the ceiling comes from
    perfmodel.serving_bound x live replicas x max_queue_s; submitting
    4x that yields explicit Overloaded rejections, bounded p99 for
    admitted requests, and no silent queue growth."""
    d = _bank()
    fleet = _fleet(
        d, _cfg(max_it=40), tmp_path, replicas=1,
        buckets=((1, (12, 12)),), max_wait_ms=0.0,
        max_queue_depth=None, min_queue_depth=2, max_queue_s=0.05,
    )
    try:
        # one served request measures the iteration rate; the monitor
        # then derives the ceiling from serving_bound
        x0, m0 = _reqs(1)[0]
        fleet.reconstruct(x0 * m0, mask=m0)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            ev = obs.read_events(str(tmp_path))
            if any(e["type"] == "fleet_ceiling" for e in ev):
                break
            time.sleep(0.02)
        ceil_ev = [e for e in ev if e["type"] == "fleet_ceiling"]
        assert ceil_ev, "ceiling was never derived from serving_bound"
        assert ceil_ev[-1]["source"] == "serving_bound"
        ceiling = fleet.queue_ceiling
        assert ceiling >= 2

        admitted, rejected = [], 0
        for i, (x, m) in enumerate(_reqs(4 * ceiling, seed=7)):
            try:
                admitted.append(
                    fleet.submit(x * m, mask=m, key=f"d{i}")
                )
            except Overloaded as e:
                rejected += 1
                assert e.retry_after_s > 0
        results = [f.result(timeout=300) for f in admitted]
        st = fleet.stats()
    finally:
        fleet.close()
    assert rejected >= 1, "4x the derived ceiling must overflow it"
    assert len(results) == len(admitted)
    assert st["p99_latency_s"] is not None and st["p99_latency_s"] < 120
    events = obs.read_events(str(tmp_path))
    rej = [e for e in events if e["type"] == "fleet_admission_reject"]
    max_ceil = max(
        e["ceiling"] for e in events if e["type"] == "fleet_ceiling"
    )
    assert all(
        e["queue_depth"] <= max(max_ceil, 64) for e in rej
    )  # bounded, never silent growth


def test_overload_ladder_rungs_and_degrade_recycle(tmp_path):
    """White-box walk of the three-rung ladder: shed micro-batch
    waiting -> reject -> (sustained) degrade-recycle onto a reduced
    max_it, then restore on pressure release — each transition an obs
    event, the degrade rungs rebuilding replicas one at a time."""
    d = _bank()
    fleet = _fleet(
        d, _cfg(max_it=8), tmp_path, replicas=1,
        max_wait_ms=50.0,
        max_queue_depth=10, degrade_after_s=0.2,
        degrade_max_it_factor=0.5,
        health_interval_s=30.0,  # the monitor must not fight the test
    )
    try:
        rep0 = fleet._replicas[0]
        assert fleet.overload_rung == "normal"
        fleet._eval_rungs(6, time.monotonic())  # 0.6 of ceiling
        assert fleet.overload_rung == "shed_batching"
        assert rep0.engine._max_wait_s == 0.0  # rung 1 sheds waits
        fleet._eval_rungs(10, time.monotonic())
        assert fleet.overload_rung == "reject"
        time.sleep(0.3)  # sustain rejection past degrade_after_s
        fleet._eval_rungs(10, time.monotonic())
        assert fleet.overload_rung == "degrade"
        # the recycle rebuilds the replica on the degraded budget
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            cur = fleet._replicas[0]
            if cur.generation == 1 and cur.state == "live":
                break
            time.sleep(0.05)
        assert fleet._replicas[0].generation == 1
        assert fleet._replicas[0].engine.cfg.max_it == 4  # 8 x 0.5
        # a request served under rung 3 uses the degraded budget
        x, m = _reqs(1)[0]
        res = fleet.reconstruct(x * m, mask=m, timeout=120)
        assert int(res.trace.num_iters) <= 4
        # pressure released: back to normal, full budget restored
        fleet._eval_rungs(0, time.monotonic())
        assert fleet.overload_rung == "normal"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            cur = fleet._replicas[0]
            if cur.generation == 2 and cur.state == "live":
                break
            time.sleep(0.05)
        assert fleet._replicas[0].engine.cfg.max_it == 8
        # recycles are maintenance, not failures: the crash-restart
        # budget must be untouched by the two rebuild cycles
        assert fleet._restarts.get(0, 0) == 0
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path))
    trans = [
        (e["rung_from"], e["rung_to"])
        for e in events if e["type"] == "fleet_overload"
    ]
    assert trans == [
        ("normal", "shed_batching"),
        ("shed_batching", "reject"),
        ("reject", "degrade"),
        ("degrade", "normal"),
    ]
    degrades = [e for e in events if e["type"] == "degrade"]
    assert [e["rung"] for e in degrades] == [
        "serve_max_it", "serve_restore"
    ]
    assert all(e["replica_id"] is None for e in degrades)


# ------------------------------------------------------------- report


def test_obs_report_fleet_section(tmp_path):
    d = _bank()
    fleet = _fleet(d, _cfg(), tmp_path, replicas=2)
    try:
        for i, (x, m) in enumerate(_reqs(4)):
            fleet.submit(x * m, mask=m, key=f"r{i}")
        # drain through close
    finally:
        fleet.close()
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(
            os.path.dirname(__file__), "..", "scripts", "obs_report.py"
        ),
    )
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    events = obs.read_events(str(tmp_path), recursive=True)
    out = obs_report.render(events)
    assert "FLEET" in out
    assert "replica 0:" in out and "replica 1:" in out
    assert "delivered     4 request(s)" in out
    assert "serve_fleet" in out


def test_check_replicas_staleness_rule(tmp_path):
    """A replica whose newest heartbeat lags the stream is stale by
    the same rule as check_peers; judged from parsed events too."""
    from ccsc_code_iccv2017_tpu.utils import watchdog

    t0 = 1000.0
    events = [
        {"t": t0, "type": "fleet_heartbeat", "replica_id": 0,
         "state": "live", "served": 3, "restarts": 0},
        {"t": t0 + 300.0, "type": "fleet_heartbeat", "replica_id": 1,
         "state": "live", "served": 5, "restarts": 1},
        {"t": t0 + 301.0, "type": "fleet_request", "replica_id": 1,
         "key": "x"},
    ]
    rows = watchdog.check_replicas(events=events, stale_s=120.0)
    assert [r["replica"] for r in rows] == [0, 1]
    assert rows[0]["stale"] is True
    assert rows[1]["stale"] is False
    assert rows[1]["served"] == 5 and rows[1]["restarts"] == 1


# --------------------------------------------------------------- lint


SERVE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "ccsc_code_iccv2017_tpu", "serve"
)


def test_serve_fleet_events_route_through_emit():
    """Thin wrapper over the migrated `emit-routing` analysis check
    (ccsc_code_iccv2017_tpu/analysis/conventions.py): every obs event
    the serving layer emits must ride through its module's ``_emit``
    — the single point that stamps ``replica_id`` — so per-replica
    health attribution can never silently regress. A new direct
    ``_run.event("serve_...")`` call fails here, not in a 3am
    incident review. The full suite runs in tests/test_analysis.py."""
    from ccsc_code_iccv2017_tpu.analysis import core

    pkg_root = os.path.normpath(os.path.join(SERVE_DIR, ".."))
    project = core.Project(
        [pkg_root], repo_root=os.path.dirname(pkg_root)
    )
    offenders = core.run_checks(project, ["emit-routing"])
    assert not offenders, "\n".join(f.render() for f in offenders)


def test_set_replica_count_grow_shrink(tmp_path):
    """The elasticity actuator end to end (ISSUE 17 tentpole): grow
    1 -> 2 spawns a second replica onto the next free slice
    synchronously, shrink 2 -> 1 drain-then-retires (never a kill —
    the retired replica's in-flight work completes or requeues), and
    a re-grow resurrects the retired slot on a fresh generation.
    Requests keep being served across every transition, zero lost."""
    d = _bank()
    fleet = _fleet(d, _cfg(), tmp_path, replicas=1)
    try:
        for i, (x, m) in enumerate(_reqs(4, seed=3)):
            assert fleet.submit(
                x * m, mask=m, key=f"g0-{i}"
            ).result(timeout=120) is not None

        r = fleet.set_replica_count(2, reason="test_grow")
        assert r == {"from_n": 1, "to_n": 2}
        snap = fleet.control_snapshot()
        assert snap["live_replicas"] == 2
        assert fleet.replica_target == 2
        for i, (x, m) in enumerate(_reqs(4, seed=4)):
            assert fleet.submit(
                x * m, mask=m, key=f"g1-{i}"
            ).result(timeout=120) is not None

        r = fleet.set_replica_count(1, reason="test_shrink")
        assert r == {"from_n": 2, "to_n": 1}
        assert fleet.replica_target == 1
        # drain-then-retire completes asynchronously
        deadline = time.monotonic() + 60
        retired = []
        while time.monotonic() < deadline and not retired:
            retired = [
                e for e in obs.read_events(str(tmp_path))
                if e["type"] == "fleet_replica_retired"
            ]
            time.sleep(0.02)
        assert retired, "shrink never retired a replica"
        assert "scale_down" in retired[-1]["reason"]
        for i, (x, m) in enumerate(_reqs(4, seed=5)):
            assert fleet.submit(
                x * m, mask=m, key=f"s0-{i}"
            ).result(timeout=120) is not None
        assert fleet.control_snapshot()["live_replicas"] == 1

        # resurrect the retired slot: same id, next generation
        fleet.set_replica_count(2, reason="test_regrow")
        assert fleet.control_snapshot()["live_replicas"] == 2
        for i, (x, m) in enumerate(_reqs(4, seed=6)):
            assert fleet.submit(
                x * m, mask=m, key=f"g2-{i}"
            ).result(timeout=120) is not None
        st = fleet.stats()
    finally:
        fleet.close()
    assert st["n_requests"] == 16 and st["n_failed"] == 0
    events = obs.read_events(str(tmp_path))
    scales = [e for e in events if e["type"] == "fleet_scale"]
    assert [(e["from_n"], e["to_n"]) for e in scales] == [
        (1, 2), (2, 1), (1, 2)
    ]
    gens = [
        e.get("generation")
        for e in events
        if e["type"] == "fleet_replica_ready"
    ]
    assert max(g for g in gens if g is not None) >= 1  # resurrection


def test_ceiling_recomputed_on_replica_death(tmp_path, monkeypatch):
    """ISSUE 17 satellite: the derived admission ceiling must be
    recomputed on EVERY replica lifecycle transition. Kill one of two
    replicas (no restart budget -> abandoned): the abandon transition
    itself must re-derive and emit ``fleet_ceiling`` with
    live_replicas=1 and a LOWER ceiling — a fleet that keeps admitting
    at 2-replica capacity into 1 replica melts down."""
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REQ", "4")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_KILL_REPLICA", "0")
    faults.reset()
    d = _bank()
    fleet = _fleet(
        d, _cfg(max_it=40), tmp_path, replicas=2, max_restarts=0,
        max_queue_depth=None, min_queue_depth=4, max_queue_s=2.0,
    )
    try:
        # a small first wave measures rates WITHOUT reaching replica
        # 0's 4th take, so the 2-replica ceiling is derived first
        for i, (x, m) in enumerate(_reqs(3, seed=11)):
            fleet.submit(x * m, mask=m, key=f"w0-{i}").result(
                timeout=300
            )
        deadline = time.monotonic() + 30
        pre = []
        while time.monotonic() < deadline and not pre:
            pre = [
                e for e in obs.read_events(str(tmp_path))
                if e["type"] == "fleet_ceiling"
                and e["live_replicas"] == 2
            ]
            time.sleep(0.02)
        assert pre, "2-replica ceiling never derived"

        # now push replica 0 over its kill threshold; requeue hands
        # its stranded work to the survivor, so nothing is lost
        dead = False
        for wave in range(12):
            for i, (x, m) in enumerate(_reqs(4, seed=20 + wave)):
                fleet.submit(
                    x * m, mask=m, key=f"w{wave + 1}-{i}"
                ).result(timeout=300)
            dead = any(
                e["type"] == "fleet_replica_abandoned"
                for e in obs.read_events(str(tmp_path))
            )
            if dead:
                break
        assert dead, "the kill fault never abandoned replica 0"

        deadline = time.monotonic() + 30
        post = []
        while time.monotonic() < deadline and not post:
            post = [
                e for e in obs.read_events(str(tmp_path))
                if e["type"] == "fleet_ceiling"
                and e["live_replicas"] == 1
            ]
            time.sleep(0.02)
    finally:
        fleet.close()
    assert post, "no ceiling recompute on the abandon transition"
    pre_ceiling = max(e["ceiling"] for e in pre)
    assert post[-1]["ceiling"] < pre_ceiling, (
        f"ceiling must drop with the lost replica: "
        f"{post[-1]['ceiling']} !< {pre_ceiling}"
    )
    assert post[-1]["source"] == "serving_bound"


# ------------------------------- request lifecycle (ISSUE 19)


def test_deadline_refused_at_admission(tmp_path):
    """A request whose budget is already spent at submit is refused
    with ``DeadlineExceeded(where='admission')`` BEFORE any admission
    work — asserted from the exception, the live counter, and the
    event stream (the refusal never becomes a served request)."""
    from ccsc_code_iccv2017_tpu.serve import DeadlineExceeded

    d = _bank()
    fleet = _fleet(d, _cfg(), tmp_path, replicas=1)
    try:
        x, m = _reqs(1)[0]
        with pytest.raises(DeadlineExceeded) as ei:
            fleet.submit(x * m, mask=m, key="doa", deadline_ms=0.0)
        assert ei.value.where == "admission"
        assert (
            fleet.metrics()["counters"]["deadline_exceeded_total"]
            == 1
        )
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path), recursive=True)
    refusals = [
        e for e in events if e["type"] == "deadline_exceeded"
    ]
    assert len(refusals) == 1
    assert refusals[0]["where"] == "admission"
    assert not any(e["type"] == "fleet_request" for e in events)


def test_deadline_expires_in_queue_never_occupies_slot(
    tmp_path, monkeypatch
):
    """Deadline honesty at the queue: while the only replica is held
    by a slow request, a queued request whose budget expires is
    dropped at the next take (``where='queue'``) — its future fails
    with DeadlineExceeded, it NEVER occupies a solve slot (no
    fleet_request, no attempt span), and its root span closes
    ``deadline``."""
    from concurrent.futures import TimeoutError as FutTimeout

    from ccsc_code_iccv2017_tpu.serve import DeadlineExceeded

    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_S", "1.0")
    faults.reset()
    d = _bank()
    # slots=1: the slow request and the doomed one can never share a
    # batch, so the expiry deterministically happens at the queue
    fleet = _fleet(
        d, _cfg(), tmp_path, replicas=1, buckets=((1, (12, 12)),)
    )
    try:
        (x0, m0), (x1, m1) = _reqs(2)
        f0 = fleet.submit(x0 * m0, mask=m0, key="slowed")
        f1 = fleet.submit(
            x1 * m1, mask=m1, key="doomed", deadline_ms=100.0
        )
        assert f0.result(timeout=120) is not None
        with pytest.raises(DeadlineExceeded) as ei:
            f1.result(timeout=120)
        assert ei.value.where == "queue"
    except FutTimeout:  # pragma: no cover - diagnosis aid
        pytest.fail("expired request never resolved")
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path), recursive=True)
    exp = [
        e for e in events
        if e["type"] == "deadline_exceeded"
        and e.get("key") == "doomed"
    ]
    assert len(exp) == 1 and exp[0]["where"] == "queue"
    assert not any(
        e["type"] == "fleet_request" and e["key"] == "doomed"
        for e in events
    )
    roots = [
        e for e in events
        if e["type"] == "span_end" and e.get("span") == "request"
        and e.get("status") == "deadline"
    ]
    assert len(roots) == 1


def test_cancel_withdraws_queued_request(tmp_path, monkeypatch):
    """Cooperative cancellation: cancelling a future while its
    request still waits in the fleet queue withdraws it pre-dispatch
    — counted, span-closed ``cancelled``, never served."""
    from concurrent.futures import CancelledError

    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_S", "1.0")
    faults.reset()
    d = _bank()
    fleet = _fleet(
        d, _cfg(), tmp_path, replicas=1, buckets=((1, (12, 12)),)
    )
    try:
        (x0, m0), (x1, m1) = _reqs(2)
        f0 = fleet.submit(x0 * m0, mask=m0, key="busy")
        f1 = fleet.submit(x1 * m1, mask=m1, key="bail")
        assert f1.cancel()  # still queued: withdrawal must succeed
        assert f0.result(timeout=120) is not None
        with pytest.raises(CancelledError):
            f1.result(timeout=120)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if fleet.control_snapshot()["cancelled"] == 1:
                break
            time.sleep(0.02)
        assert fleet.control_snapshot()["cancelled"] == 1
        assert fleet.metrics()["counters"]["cancelled_total"] == 1
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path), recursive=True)
    cans = [
        e for e in events if e["type"] == "request_cancelled"
    ]
    assert len(cans) == 1 and cans[0]["key"] == "bail"
    assert cans[0]["where"] == "queue"
    assert not any(
        e["type"] == "fleet_request" and e["key"] == "bail"
        for e in events
    )
    roots = [
        e for e in events
        if e["type"] == "span_end" and e.get("span") == "request"
        and e.get("status") == "cancelled"
    ]
    assert len(roots) == 1


def test_hedge_routes_around_slow_replica_and_suppresses_loser(
    tmp_path, monkeypatch
):
    """Hedged attempts, in-process: with replica 0 slow (not hung),
    stuck attempts get a duplicate on replica 1; the first result
    wins, every key is delivered exactly once and bit-identical to a
    single unfaulted engine, the loser is suppressed-and-counted
    (``hedge_lost`` event + attempt span), and the hedge volume
    respects the hedge_max_frac denominator."""
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REQ", "1")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_S", "1.0")
    monkeypatch.setenv("CCSC_FAULT_ENGINE_SLOW_REPLICA", "0")
    faults.reset()
    d = _bank()
    cfg = _cfg()
    reqs = _reqs(6)
    ref = _single_engine_results(d, cfg, reqs)
    fleet = _fleet(
        d, cfg, tmp_path, replicas=2, hedge_after_ms=100.0,
        hedge_max_frac=1.0, health_interval_s=0.02,
    )
    try:
        futs = [
            fleet.submit(x * m, mask=m, key=f"h{i}")
            for i, (x, m) in enumerate(reqs)
        ]
        res = [f.result(timeout=120) for f in futs]
        snap = fleet.control_snapshot()
        assert snap["hedges"] >= 1
        assert snap["hedges"] <= 1.0 * len(reqs)  # the frac cap
        assert snap["hedge_wins"] >= 1
    finally:
        fleet.close()  # joins workers: straggler losers settle
    for i in range(len(reqs)):
        np.testing.assert_array_equal(res[i].recon, ref[i].recon)
    events = obs.read_events(str(tmp_path), recursive=True)
    served = [e for e in events if e["type"] == "fleet_request"]
    keys = [e["key"] for e in served]
    assert sorted(keys) == sorted(f"h{i}" for i in range(6))
    assert len(keys) == len(set(keys))  # exactly once each
    spawns = {
        e["key"] for e in events if e["type"] == "hedge_spawn"
    }
    wins = {e["key"] for e in events if e["type"] == "hedge_win"}
    losses = {e["key"] for e in events if e["type"] == "hedge_lost"}
    assert spawns
    assert wins <= spawns and losses <= spawns
    assert wins == losses  # every decided pair: winner + loser
    lost_spans = [
        e for e in events
        if e["type"] == "span_end" and e.get("span") == "attempt"
        and e.get("status") == "hedge_lost"
    ]
    assert len(lost_spans) == len(losses)


def test_tenant_deadline_default_stamped_on_trace(tmp_path):
    """``TenantSpec.deadline_ms`` is the tenant's default budget: the
    resolved ABSOLUTE deadline is stamped on the request's root span
    at admission (deadline honesty starts at the trace), and a
    comfortable budget serves normally."""
    from ccsc_code_iccv2017_tpu.config import TenantSpec

    d = _bank()
    fleet = _fleet(
        d, _cfg(), tmp_path, replicas=1,
        tenants=(
            TenantSpec(tenant="mobile", deadline_ms=60_000.0),
        ),
    )
    try:
        x, m = _reqs(1)[0]
        res = fleet.submit(
            x * m, mask=m, key="t0", tenant="mobile"
        ).result(timeout=120)
        assert res is not None
    finally:
        fleet.close()
    events = obs.read_events(str(tmp_path), recursive=True)
    roots = [
        e for e in events
        if e["type"] == "span_start" and e.get("span") == "request"
    ]
    assert len(roots) == 1
    dl = roots[0].get("deadline")
    assert dl is not None and dl > time.time() - 120
