"""MATLAB-anchored golden trajectory for the 3D VIDEO LEARNER.

Fourth anchor in the series (tests/test_matlab_anchor.py inpainting,
test_matlab_anchor_learn.py 2D consensus, test_matlab_anchor_masked.py
hyperspectral): a LITERAL, line-ordered float64 NumPy transcription of
3D/admm_learn_conv3D_large.m — the ND (fftn) consensus learner — run
against the framework's dimension-generic learner at
ProblemGeom((s,s,s), k).

What this anchors beyond the 2D learner anchor:
- the ND FFT boundary (fftn over 3 spatial dims, :25,44,53; the
  reference's objectiveFunction builds its fftn indexing with eval'd
  strings :350-357 — the framework's rfftn_spatial/irfftn_spatial
  must agree through the half-spectrum),
- the ND circular kernel embedding/extraction (init :39-40 pads
  randn(kernel_size) post and circshifts by -psf_radius in ALL THREE
  dims; KernelConstraintProj :239-254 shifts/crops/projects/re-embeds
  in 3D),
- the 3D file's z bookkeeping: z is ONE GLOBAL randn array (:48) — so
  each consensus block codes a DIFFERENT slice (unlike dzParallel.m:44
  which repmat's one shared z to every block) — with a single global
  dual (:92) and the z-solve at rho=1 against BLOCK 1's unprojected
  local dictionary (:141-142 d_hat = D_hat{1}, :161), the
  compat_coding='block1' semantic,
- the 3D rho point: rho_d=5000 (:109,:125), rho_z=1 (:175), sparsity
  threshold = lambda (ProxSparse(z + d_Z, lambda(2)) :168).

DISCLOSED deviations (same two as the 2D learner anchor): inner-loop
tol breaks are elided (tests run tol=0, :149,:189 never trigger), and
the transcription is run z-globally exactly as the text (no block
split of z needed — test_dparallel_z_global_equals_block_local proved
global and block-local z bookkeeping coincide, and the same per-image
decoupling argument applies verbatim in ND).

The framework side shares no code or structure with the transcription
(rfft half-spectra, einsum Woodbury over a real Cholesky embedding,
lax.scan inner loops, one dimension-generic code path for 2D/3D/4D).
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
from ccsc_code_iccv2017_tpu.parallel import consensus

AXES3 = (0, 1, 2)


def fftn3(x):
    """fftn over the 3 leading (spatial) dims (:25,44,53)."""
    return np.fft.fftn(x, axes=AXES3)


def ifftn3(x):
    return np.fft.ifftn(x, axes=AXES3)


def kernel_constraint_proj(u, r):
    """KernelConstraintProj (:232-256), 3D: circshift to support,
    crop, per-filter unit-ball projection where the norm exceeds 1,
    re-pad post, shift back."""
    s = 2 * r + 1
    up = np.roll(u, (r, r, r), AXES3)  # :239
    up = up[:s, :s, :s, :]  # :240
    un = np.broadcast_to(
        np.sum(up**2, axis=AXES3, keepdims=True), up.shape
    )  # :245
    up = np.where(
        un >= 1, up / np.sqrt(np.where(un >= 1, un, 1.0)), up
    )  # :246-248
    full = np.zeros_like(u)
    full[:s, :s, :s, :] = up  # :253 padarray post
    return np.roll(full, (-r, -r, -r), AXES3)  # :254


def precompute_H_hat_D(z_hat, rho):
    """precompute_H_hat_D (:258-273): per-frequency A = [ni, k] code
    matrix (col-major flatten over the 3 spatial dims, permute
    [3,2,1] :268) and its pinv-based Woodbury inverse (:271)."""
    sx, sy, sz, k, ni = z_hat.shape
    ss = sx * sy * sz
    zf = np.reshape(z_hat, (ss, k, ni), order="F")
    Ainv = np.empty((ss, k, k), complex)
    for f in range(ss):
        A = zf[f].T  # [ni, k]
        Ainv[f] = (
            np.eye(k)
            - A.conj().T
            @ np.linalg.pinv(rho * np.eye(ni) + A @ A.conj().T)
            @ A
        ) / rho  # :271
    return zf, Ainv


def solve_conv_term_D(zf, Ainv, ud_hat, Bh, rho):
    """solve_conv_term_D (:288-312): x_f = Sinv (A' b + rho c)."""
    sx, sy, sz, k = ud_hat.shape
    ss = sx * sy * sz
    ni = Bh.shape[3]
    Bf = np.reshape(Bh, (ss, ni), order="F")  # :301
    cf = np.reshape(ud_hat, (ss, k), order="F")  # :302
    x = np.empty((ss, k), complex)
    for f in range(ss):
        A = zf[f].T
        x[f] = Ainv[f] @ (A.conj().T @ Bf[f] + rho * cf[f])  # :305
    return np.reshape(x, (sx, sy, sz, k), order="F")  # :310


def precompute_H_hat_Z(dhat):
    """precompute_H_hat_Z (:275-286)."""
    sx, sy, sz, k = dhat.shape
    dhat_flat = np.reshape(dhat, (sx * sy * sz, k), order="F")  # :283
    dhatTdhat = np.sum(np.conj(dhat_flat) * dhat_flat, axis=1)  # :284
    return dhat_flat, dhatTdhat


def solve_conv_term_Z(dhat_flat, dhatTdhat, ud_hat, B_hat, rho):
    """solve_conv_term_Z (:314-337): per-frequency Sherman-Morrison;
    dhatT(k,f) = conj(dhat_flat(f,k)) (:162), so
    sum(conj(dhatT).*b, 1) is sum_k dhat_k b_k (:334)."""
    sx, sy, sz, k, n = ud_hat.shape
    ss = sx * sy * sz
    Bf = np.reshape(B_hat, (ss, n), order="F")
    zf = np.reshape(ud_hat, (ss, k, n), order="F")
    bvec = (
        np.conj(dhat_flat)[:, :, None] * Bf[:, None, :] + rho * zf
    )  # :331
    corr = np.einsum("fk,fkn->fn", dhat_flat, bvec)
    zh = (
        bvec / rho
        - (1.0 / (rho + dhatTdhat))[:, None, None]
        * np.conj(dhat_flat)[:, :, None]
        * corr[:, None, :]
        / rho
    )  # :334
    return np.reshape(zh, (sx, sy, sz, k, n), order="F")


def prox_sparse(u, theta):
    """ProxSparse = max(0, 1 - theta/|u|) .* u (:33)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
    return np.maximum(0.0, f) * u


def matlab_3d_learner(
    b, d0_full, z0, N, r, lam_res, lam_pri, max_it, max_it_d, max_it_z
):
    """Transcription of the admm_learn_conv3D_large.m main loop
    (:100-215) at its hardcoded rho point (5000 d-side :109,:125; 1
    z-side :175; threshold lambda :168), z kept as the text's single
    global array (:48,:92,:168-179).

    b: [H, H, H, n] unpadded; d0_full: [sx, sy, sz, k] the :39-40
    init (already embedded + circshifted); z0: [sx, sy, sz, k, n] the
    :48 global randn. Returns (obj_vals_d, obj_vals_z), length
    max_it + 1 (index 0 = the :65 initial objective).
    """
    H = b.shape[0]
    n = b.shape[-1]
    ni = n // N
    sx = H + 2 * r
    k = d0_full.shape[3]

    B = np.zeros((sx, sx, sx, n))
    B[r : r + H, r : r + H, r : r + H, :] = b  # :23 padarray both
    B_hat = fftn3(B)  # :24-26
    Bh = [B_hat[..., nn * ni : (nn + 1) * ni] for nn in range(N)]  # :27-29

    D = [d0_full.copy() for _ in range(N)]  # :41
    dup = [fftn3(d0_full) for _ in range(N)]  # :42-46
    z = z0.copy()  # :48 (GLOBAL)
    z_hat = fftn3(z)  # :51-55

    Dbar = np.zeros((sx, sx, sx, k))  # :88
    Udbar = np.zeros((sx, sx, sx, k))  # :89
    d_D = [np.zeros((sx, sx, sx, k)) for _ in range(N)]  # :90
    d_Z = np.zeros((sx, sx, sx, k, n))  # :92 (GLOBAL)

    def objective(zc, d_spatial):
        # objectiveFunction (:341-377): d_hat from the SPATIAL block-1
        # filters, Dz per image, crop psf_radius in all 3 dims
        dh = fftn3(d_spatial)  # :350-352
        Dz = np.real(
            ifftn3(np.sum(fftn3(zc) * dh[..., None], axis=3))
        )  # :365-370
        crop = Dz[r : sx - r, r : sx - r, r : sx - r, :]  # :371
        f_z = lam_res * 0.5 * np.sum((crop - b) ** 2)  # :372
        g_z = lam_pri * np.sum(np.abs(zc))  # :374
        return f_z + g_z

    obj0 = objective(z, D[0])  # :65
    obj_vals_d, obj_vals_z = [obj0], [obj0]

    for _ in range(max_it):  # :100
        # ---- D pass ------------------------------------------ :106-153
        pre = []
        for nn in range(N):  # :106-110
            zup = z_hat[..., nn * ni : (nn + 1) * ni]  # :108
            pre.append(precompute_H_hat_D(zup, 5000.0))  # :109
        for _i_d in range(max_it_d):  # :114
            u_D2 = kernel_constraint_proj(Dbar + Udbar, r)  # :118
            for nn in range(N):
                d_D[nn] = d_D[nn] + (D[nn] - u_D2)  # :121
                ud = fftn3(u_D2 - d_D[nn])  # :123
                dup[nn] = solve_conv_term_D(
                    pre[nn][0], pre[nn][1], ud, Bh[nn], 5000.0
                )  # :125
                D[nn] = np.real(ifftn3(dup[nn]))  # :127
            Dbar = sum(D) / N  # :130-135
            Udbar = sum(d_D) / N  # :136
        d = D[0]  # :141
        d_hat = dup[0]  # :142
        obj_vals_d.append(objective(z, d))  # :146 (after last inner)

        # ---- Z pass ------------------------------------------ :160-192
        dhat_flat, dd = precompute_H_hat_Z(d_hat)  # :161
        for _i_z in range(max_it_z):  # :164
            u_Z2 = prox_sparse(z + d_Z, lam_pri)  # :168 theta = lambda
            d_Z = d_Z + (z - u_Z2)  # :169
            ud = fftn3(u_Z2 - d_Z)  # :170-174
            z_hat = solve_conv_term_Z(dhat_flat, dd, ud, B_hat, 1.0)  # :175
            z = np.real(ifftn3(z_hat))  # :176-180
        obj_vals_z.append(objective(z, d))  # :186

    return np.array(obj_vals_d), np.array(obj_vals_z)


def _problem(seed=55, H=6, s=3, k=3, n=4, N=2):
    """Tiny fixed-seed 3D problem + the :39-48 init arrays
    (ni = sqrt(n) = n/N, :11-12)."""
    rng = np.random.default_rng(seed)
    r = s // 2
    sx = H + 2 * r
    b = rng.uniform(0.1, 1.0, (H, H, H, n))
    d0 = rng.normal(size=(s, s, s, k))  # :39 randn(kernel_size)
    d0_full = np.zeros((sx, sx, sx, k))
    d0_full[:s, :s, :s, :] = d0  # :39 padarray post
    d0_full = np.roll(d0_full, (-r, -r, -r), AXES3)  # :40 circshift
    z0 = rng.normal(size=(sx, sx, sx, k, n))  # :48 global randn
    return b, d0_full, z0, r


def _run_framework(b, d0_full, z0, N, cfg):
    """Drive the framework's dimension-generic learner from the MATLAB
    init verbatim: every block's d_local = the :39-40 embedding, z =
    each block's SLICE of the :48 global randn, all duals and
    Dbar/Udbar zero (:88-92)."""
    H = b.shape[0]
    n = b.shape[-1]
    ni = n // N
    k = d0_full.shape[3]
    s = d0_full.shape[0] - H + 1  # sx - H = 2r
    geom = ProblemGeom((s, s, s), k)
    fg = common.FreqGeom.create(geom, (H, H, H))
    d_fw = jnp.asarray(np.moveaxis(d0_full, -1, 0), jnp.float32)
    # z0 [sx,sy,sz,k,n] -> [N, ni, k, sx, sy, sz] (per-block slices)
    z_np = np.transpose(z0, (4, 3, 0, 1, 2)).reshape(
        N, ni, k, *fg.spatial_shape
    )
    z_fw = jnp.asarray(z_np, jnp.float32)
    state = learn_mod.LearnState(
        d_local=jnp.broadcast_to(d_fw, (N, *d_fw.shape)),
        dual_d=jnp.zeros((N, *d_fw.shape), jnp.float32),
        dbar=jnp.zeros_like(d_fw),
        udbar=jnp.zeros_like(d_fw),
        z=z_fw,
        dual_z=jnp.zeros_like(z_fw),
    )
    b_blocks = jnp.asarray(
        np.transpose(b, (3, 0, 1, 2)).reshape(N, ni, H, H, H), jnp.float32
    )
    step = consensus.make_outer_step(geom, cfg, fg, mesh=None)
    obj_d, obj_z = [], []
    for _ in range(cfg.max_it):
        state, m = step(state, b_blocks)
        obj_d.append(float(m.obj_d))
        obj_z.append(float(m.obj_z))
    return np.array(obj_d), np.array(obj_z)


def test_3d_learner_matches_matlab_transcription():
    """Framework at ProblemGeom((3,3,3), k) with the 3D file's rho
    point (5000/1, threshold lambda) and compat_coding='block1' must
    reproduce the transcription's obj_d/obj_z trajectories to float32
    tolerance — anchoring the ND FFT boundary, ND kernel projection,
    and consensus bookkeeping against the MATLAB text."""
    b, d0_full, z0, r = _problem()
    N, max_it = 2, 2
    ml_d, ml_z = matlab_3d_learner(
        b, d0_full, z0, N, r, 1.0, 1.0, max_it, 5, 5
    )
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=max_it,
        tol=0.0,
        max_it_d=5,
        max_it_z=5,
        rho_d=5000.0,
        rho_z=1.0,
        num_blocks=N,
        verbose="none",
        track_objective=True,
        compat_coding="block1",
    )
    fw_d, fw_z = _run_framework(b, d0_full, z0, N, cfg)
    np.testing.assert_allclose(fw_d, ml_d[1:], rtol=2e-3)
    np.testing.assert_allclose(fw_z, ml_z[1:], rtol=2e-3)
    # trajectory must actually move (no trivial agreement)
    assert ml_z[-1] < 0.5 * ml_z[0]
