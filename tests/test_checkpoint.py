"""Checkpoint/resume (utils/checkpoint.py): an interrupted run resumed
from its snapshot must reproduce the uninterrupted run exactly — the
snapshot carries the FULL ADMM state including duals, which a
filters-only warm start would lose."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked


def test_consensus_checkpoint_resume(tmp_path):
    ck = str(tmp_path / "ck")
    geom = ProblemGeom((3, 3), 4)
    b = jnp.asarray(
        np.asarray(
            jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)),
            np.float32,
        )
    )
    mk = lambda it: LearnConfig(
        max_it=it, max_it_d=2, max_it_z=2, num_blocks=2,
        rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
        track_objective=True,
    )
    full = learn(b, geom, mk(4), key=jax.random.PRNGKey(0))
    # interrupted: 2 iterations, checkpointed every iteration
    learn(
        b, geom, mk(2), key=jax.random.PRNGKey(0),
        checkpoint_dir=ck, checkpoint_every=1,
    )
    resumed = learn(
        b, geom, mk(4), key=jax.random.PRNGKey(0),
        checkpoint_dir=ck, checkpoint_every=1,
    )
    np.testing.assert_allclose(
        np.asarray(resumed.d), np.asarray(full.d), atol=2e-5
    )
    np.testing.assert_allclose(
        resumed.trace["obj_vals_z"], full.trace["obj_vals_z"], rtol=1e-4
    )
    # shape-mismatched checkpoint is rejected, not silently used
    import pytest

    with pytest.raises(ValueError):
        learn(
            b, ProblemGeom((3, 3), 5), mk(4), key=jax.random.PRNGKey(0),
            checkpoint_dir=ck,
        )


def test_masked_checkpoint_resume(tmp_path):
    ck = str(tmp_path / "ck")
    geom = ProblemGeom((3, 3), 3, reduce_shape=(2,))
    r = np.random.default_rng(0)
    b = jnp.asarray(r.uniform(0.1, 1.0, (2, 2, 10, 10)).astype(np.float32))
    mk = lambda it: LearnConfig(
        max_it=it, max_it_d=2, max_it_z=2, tol=0.0, verbose="none",
    )
    kw = dict(gamma_div_d=50.0, gamma_div_z=10.0, key=jax.random.PRNGKey(0))
    full = learn_masked(b, geom, mk(4), **kw)
    learn_masked(
        b, geom, mk(2), checkpoint_dir=ck, checkpoint_every=1, **kw
    )
    resumed = learn_masked(
        b, geom, mk(4), checkpoint_dir=ck, checkpoint_every=1, **kw
    )
    np.testing.assert_allclose(
        np.asarray(resumed.d), np.asarray(full.d), atol=2e-5
    )
    np.testing.assert_allclose(
        resumed.trace["obj_vals_z"], full.trace["obj_vals_z"], rtol=1e-4
    )


def test_checkpoint_roundtrip_bf16_state():
    """bf16-stored code state survives save/load: np.load returns raw
    '|V2' for ml_dtypes arrays, so the checkpoint stores the uint16 bit
    pattern with a dtype sidecar and restores bfloat16 exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn import learn

    import tempfile

    r = np.random.default_rng(3)
    b = r.normal(size=(4, 12, 12)).astype(np.float32)
    geom = ProblemGeom((3, 3), 4)
    kw = dict(max_it=2, max_it_d=2, max_it_z=2, num_blocks=2,
              verbose="none", storage_dtype="bfloat16")
    with tempfile.TemporaryDirectory() as td:
        r1 = learn(jnp.asarray(b), geom, LearnConfig(**kw),
                   key=jax.random.PRNGKey(0), checkpoint_dir=td,
                   checkpoint_every=1)
        # resume from the mid-run snapshot: must restore bf16 and run
        r2 = learn(jnp.asarray(b), geom,
                   LearnConfig(**{**kw, "max_it": 3}),
                   key=jax.random.PRNGKey(0), checkpoint_dir=td,
                   checkpoint_every=1)
    assert r2.z.dtype == jnp.bfloat16
    assert len(r2.trace["obj_vals_z"]) >= len(r1.trace["obj_vals_z"])


def test_checkpoint_roundtrip_new_knobs():
    """Checkpoint/resume with the r4 execution-strategy knobs stacked
    (bf16 code + dictionary state, matmul FFT, fused z kernel): resume
    restores the storage dtypes and continues."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn import learn

    import tempfile

    r = np.random.default_rng(5)
    b = r.normal(size=(4, 12, 12)).astype(np.float32)
    geom = ProblemGeom((3, 3), 4)
    kw = dict(max_it=2, max_it_d=2, max_it_z=2, num_blocks=2,
              verbose="none", storage_dtype="bfloat16",
              d_storage_dtype="bfloat16", fft_impl="matmul",
              fused_z=True)
    with tempfile.TemporaryDirectory() as td:
        r1 = learn(jnp.asarray(b), geom, LearnConfig(**kw),
                   key=jax.random.PRNGKey(0), checkpoint_dir=td,
                   checkpoint_every=1)
        r2 = learn(jnp.asarray(b), geom,
                   LearnConfig(**{**kw, "max_it": 3}),
                   key=jax.random.PRNGKey(0), checkpoint_dir=td,
                   checkpoint_every=1)
    assert r2.z.dtype == jnp.bfloat16
    assert len(r2.trace["obj_vals_z"]) >= len(r1.trace["obj_vals_z"])
