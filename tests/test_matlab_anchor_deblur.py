"""MATLAB-anchored golden trajectory for the VIDEO DEBLUR SOLVER.

Sixth anchor in the series: a LITERAL, line-ordered float64 NumPy
transcription of 3D/Deblurring/admm_solve_video_weighted_sampling.m —
the reconstruction solver whose distinguishing mechanism is OPERATOR
COMPOSITION: the blur OTF multiplies every filter spectrum inside the
solve (:124-132) while the final reconstruction uses the clean filter
OTFs (:109), so coding "through" the blur deconvolves. Also anchored:
the prepended dirac (:5-7, still sparsified — unlike the Poisson
solver there is NO channel exemption), the symmetric-padded
smooth_init offset subtracted in the data prox (:16, :117) and added
back at the end (:109), the quadratic masked prox (:29), and the
gamma heuristic 500*lambda/max(b) at ratio 1 (:36-37).

The text contains TWO local deviations from its own intent, both
parameterized so each can be anchored AND quantified:

1. DIAGONAL SOLVE (``exact_solve``): solve_conv_term :155-156
   computes x_k = b_k / (rho + sum_j |d_j|^2) — it drops the
   Sherman-Morrison projection entirely (the correct rank-1 update
   term is conj(d_k) * sum_j d_j b_j / (rho + sum|d|^2); compare the
   inpainting solver's exact :170-190). The framework solves the
   rank-1 system exactly; ``exact_solve=True`` swaps in the exact
   closed form.

2. RHO SCALE (``rho_literal``): :146,:149 set
   rho = sw * gammas(2)/gammas(1) with sw = size(xi_hat{1},3) — the
   PADDED TEMPORAL FFT LENGTH. The same line in the demosaic solver
   (admm_solve_conv23D_weighted_sampling.m:126) scales by the
   wavelength count to compensate its W-fold data-term sum; here the
   temporal axis is an FFT dim (there is no reduce sum), so the
   scaling is a copy-paste artifact that just rescales the ADMM
   penalty by the clip length. The framework uses rho =
   gamma_ratio (models/reconstruct.py DOCUMENTED DIVERGENCES (c));
   ``rho_literal=False`` does the same.

test_deblur_matches_matlab_exact_variant anchors the framework
against the transcription with both deviations resolved to intent;
the quantification test pins that the literal diagonal solve is a
REAL divergence without anchoring to it.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ccsc_code_iccv2017_tpu.config import ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem,
    reconstruct,
)

AXES3 = (0, 1, 2)


def fftn3(x):
    return np.fft.fftn(x, axes=AXES3)


def ifftn3(x):
    return np.fft.ifftn(x, axes=AXES3)


def psf2otf3(psf, size_x):
    """MATLAB psf2otf in 3D: zero-pad, circshift the center to (1,1,1),
    fftn (:124, :130-131)."""
    full = np.zeros(size_x)
    full[: psf.shape[0], : psf.shape[1], : psf.shape[2]] = psf
    full = np.roll(
        full,
        tuple(-(s // 2) for s in psf.shape),
        AXES3,
    )
    return fftn3(full)


def prox_sparse(u, theta):
    """ProxSparse = max(0, 1 - theta/|u|) .* u (:32)."""
    with np.errstate(divide="ignore", invalid="ignore"):
        f = np.where(np.abs(u) > 0, 1.0 - theta / np.abs(u), 0.0)
    return np.maximum(0.0, f) * u


def sympad3(x, r):
    """padarray(x, psf_radius, 'symmetric', 'both') (:16); r is the
    per-axis radius tuple."""
    return np.pad(x, [(ri, ri) for ri in r], mode="symmetric")


def matlab_deblur_solver(
    b,
    kmat,
    mask,
    psf,
    smooth_init,
    lam_res,
    lam_pri,
    max_it,
    exact_solve=False,
    rho_literal=True,
):
    """Transcription of admm_solve_video_weighted_sampling.m.
    b, mask, smooth_init: [H, W, T] (one clip); kmat: [s, s, st, K];
    psf: [3, 3, 3] blur. Returns (obj_vals [max_it + 1], final res)."""
    s = kmat.shape[0]
    st = kmat.shape[2]
    # :5-7 — dirac PREPENDED
    k_dirac = np.zeros((s, s, st))
    k_dirac[s // 2, s // 2, st // 2] = 1.0
    kmat = np.concatenate([k_dirac[..., None], kmat], axis=3)
    K = kmat.shape[3]

    r = (s // 2, s // 2, st // 2)  # :10
    size_x = tuple(b.shape[i] + 2 * r[i] for i in range(3))  # :11
    ss = int(np.prod(size_x))

    # precompute_H_hat (:121-138): blur OTF times each filter OTF;
    # clean OTFs kept for the final reconstruction
    psf_hat = psf2otf3(psf, size_x)  # :124
    dhat_k = np.stack(
        [psf2otf3(kmat[..., i], size_x) for i in range(K)], axis=3
    )  # :130
    dhat = psf_hat[..., None] * dhat_k  # :131
    dhat_flat = np.reshape(dhat, (ss, K), order="F")  # :135
    dhatTdhat = np.sum(np.conj(dhat_flat) * dhat_flat, axis=1)  # :136
    dhatT = np.conj(dhat_flat.T)  # [K, ss] (:13)

    smoothinit = sympad3(smooth_init, r)  # :16

    # precompute_MProx (:114-119)
    M = np.zeros(size_x)
    M[r[0] : r[0] + b.shape[0], r[1] : r[1] + b.shape[1],
      r[2] : r[2] + b.shape[2]] = mask
    B_pad = np.zeros(size_x)
    B_pad[r[0] : r[0] + b.shape[0], r[1] : r[1] + b.shape[1],
          r[2] : r[2] + b.shape[2]] = b
    Mtb = B_pad * M - smoothinit * M  # :117

    lam = (lam_res, lam_pri)  # :35
    gamma_heuristic = 500.0 * lam_pri / np.max(b)  # :36
    gamma = (gamma_heuristic, gamma_heuristic)  # :37

    sw = size_x[2]  # :146 sw = size(xi_hat{1}, 3)
    rho = (sw if rho_literal else 1.0) * gamma[1] / gamma[0]  # :149

    def solve_conv_term(xi1_hat, xi2_hat):
        """solve_conv_term (:140-161) in its [K, ss] layout; or the
        exact Sherman-Morrison solve of the same rank-1 system."""
        bb = dhatT * np.reshape(xi1_hat, (1, ss), order="F") + (
            rho * np.reshape(xi2_hat, (ss, K), order="F").T
        )  # :152
        if exact_solve:
            corr = np.sum(dhat_flat.T * bb, axis=0, keepdims=True)
            x = bb / rho - (
                dhatT * corr / (rho + dhatTdhat)[None, :] / rho
            )
        else:
            scInverse = 1.0 / (rho + dhatTdhat)  # :155
            x = bb / rho - (
                (scInverse * dhatTdhat)[None, :] * bb / rho
            )  # :156
        return np.reshape(x.T, (*size_x, K), order="F")  # :159

    def objective(zc):
        """objectiveFunction (:163-178): BLURRED operator + smoothinit."""
        zh = np.stack([fftn3(zc[..., i]) for i in range(K)], axis=3)
        Dz = np.real(ifftn3(np.sum(dhat * zh, axis=3))) + smoothinit  # :171
        crop = Dz[r[0] : size_x[0] - r[0], r[1] : size_x[1] - r[1],
                  r[2] : size_x[2] - r[2]]
        f_z = lam_res * 0.5 * np.sum((mask * crop - mask * b) ** 2)  # :172
        g_z = lam_pri * np.sum(np.abs(zc))  # :173
        return f_z + g_z

    # init (:39-50): everything zero
    size_z = (*size_x, K)
    d1 = np.zeros(size_x)
    d2 = np.zeros(size_z)
    z = np.zeros(size_z)
    z_hat = np.zeros(size_z, complex)

    obj_vals = [objective(z)]  # :53
    for _ in range(max_it):  # :58
        v1 = np.real(ifftn3(np.sum(dhat * z_hat, axis=3)))  # :61
        v2 = z  # :62
        theta1 = lam[0] / gamma[0]
        u1 = (Mtb + (v1 - d1) / theta1) / (M + 1.0 / theta1)  # :29,:65
        u2 = prox_sparse(v2 - d2, lam[1] / gamma[1])  # :66 (NO exemption)
        d1 = d1 - (v1 - u1)  # :70
        xi1_hat = fftn3(u1 + d1)  # :73-74
        d2 = d2 - (z - u2)  # :78
        xi2 = u2 + d2  # :81
        xi2_hat = np.stack(
            [fftn3(xi2[..., q]) for q in range(K)], axis=3
        )  # :83-85
        z_hat = solve_conv_term(xi1_hat, xi2_hat)  # :92
        z = np.stack(
            [np.real(ifftn3(z_hat[..., q])) for q in range(K)], axis=3
        )  # :93-95
        obj_vals.append(objective(z))  # :101

    # final: CLEAN filter OTFs + smoothinit, crop (:109-110); no clamp
    Dz = np.real(ifftn3(np.sum(dhat_k * z_hat, axis=3))) + smoothinit
    res = Dz[r[0] : size_x[0] - r[0], r[1] : size_x[1] - r[1],
             r[2] : size_x[2] - r[2]]
    return np.array(obj_vals), res


def _problem(seed=88, H=6, s=3, K=2):
    rng = np.random.default_rng(seed)
    x = rng.uniform(0.1, 1.0, (H, H, H))
    mask = (rng.uniform(size=x.shape) > 0.3).astype(np.float64)
    b = mask * x  # the driver feeds the masked observation
    b[b == b.max()] += 0.05  # pin a unique max for the gamma heuristic
    d = rng.normal(size=(s, s, s, K))
    d /= np.sqrt(np.sum(d**2, axis=(0, 1, 2), keepdims=True))
    psf = rng.uniform(0.1, 1.0, (3, 3, 3))
    psf /= psf.sum()
    smooth_init = rng.uniform(0.2, 0.4, (H, H, H))
    return b, d, mask, psf, smooth_init


def test_deblur_matches_matlab_exact_variant():
    """Framework vs the transcription with both text deviations
    resolved to intent (exact rank-1 solve, rho = gamma ratio):
    objective trajectory and final reconstruction must match to float
    tolerance — anchoring the blur-OTF composition, clean-OTF output,
    prepended (sparsified) dirac, symmetric smooth_init plumbing, and
    the 500x gamma heuristic against the MATLAB text."""
    b, d, mask, psf, smooth_init = _problem()
    n_iters = 4
    ml_objs, ml_res = matlab_deblur_solver(
        b, d, mask, psf, smooth_init, 100.0, 0.5, n_iters,
        exact_solve=True, rho_literal=False,
    )
    geom = ProblemGeom((3, 3, 3), 2)
    prob = ReconstructionProblem(geom, dirac="prepend")
    cfg = SolveConfig(
        lambda_residual=100.0,
        lambda_prior=0.5,
        max_it=n_iters,
        tol=0.0,
        gamma_factor=500.0,
        gamma_ratio=1.0,
        verbose="none",
        track_objective=True,
    )
    res = reconstruct(
        jnp.asarray(b[None], jnp.float32),
        jnp.asarray(np.transpose(d, (3, 0, 1, 2)), jnp.float32),
        prob,
        cfg,
        mask=jnp.asarray(mask[None], jnp.float32),
        smooth_init=jnp.asarray(smooth_init[None], jnp.float32),
        blur_psf=jnp.asarray(psf, jnp.float32),
    )
    assert int(res.trace.num_iters) == n_iters
    np.testing.assert_allclose(
        np.asarray(res.trace.obj_vals[: n_iters + 1], np.float64),
        ml_objs,
        rtol=5e-4,
    )
    np.testing.assert_allclose(
        np.asarray(res.recon[0], np.float64), ml_res, atol=2e-3, rtol=2e-3
    )
    # trajectory must actually move (no trivial agreement)
    assert ml_objs[-1] < 0.75 * ml_objs[0]


def test_deblur_literal_diag_solve_quantified():
    """Deviation 1 is real: the literal :155-156 formula (which drops
    the Sherman-Morrison projection term entirely) measurably departs
    from the exact solve of the same system, while both still
    converge at the reference operating point."""
    b, d, mask, psf, smooth_init = _problem(seed=89)
    n_iters = 4
    lit, _ = matlab_deblur_solver(
        b, d, mask, psf, smooth_init, 100.0, 0.5, n_iters,
        exact_solve=False, rho_literal=True,
    )
    exact, _ = matlab_deblur_solver(
        b, d, mask, psf, smooth_init, 100.0, 0.5, n_iters,
        exact_solve=True, rho_literal=True,
    )
    assert np.all(np.isfinite(lit)) and np.all(np.isfinite(exact))
    assert lit[-1] < 0.95 * lit[0] and exact[-1] < 0.95 * exact[0]
    rel = np.abs(lit[1:] - exact[1:]) / np.abs(exact[1:])
    assert rel.max() > 1e-6


def test_deblur_literal_rho_scale_quantified():
    """Deviation 2 is real: rho = sw * gamma_ratio (the literal
    :146/:149 temporal-length scaling) measurably changes the
    trajectory versus rho = gamma_ratio, and both converge — pinning
    that the framework's unscaled rho is a deliberate divergence, not
    a transcription accident."""
    b, d, mask, psf, smooth_init = _problem(seed=90)
    n_iters = 4
    lit, _ = matlab_deblur_solver(
        b, d, mask, psf, smooth_init, 100.0, 0.5, n_iters,
        exact_solve=True, rho_literal=True,
    )
    intent, _ = matlab_deblur_solver(
        b, d, mask, psf, smooth_init, 100.0, 0.5, n_iters,
        exact_solve=True, rho_literal=False,
    )
    assert np.all(np.isfinite(lit)) and np.all(np.isfinite(intent))
    assert lit[-1] < 0.95 * lit[0] and intent[-1] < 0.95 * intent[0]
    rel = np.abs(lit[1:] - intent[1:]) / np.abs(intent[1:])
    assert rel.max() > 1e-6
