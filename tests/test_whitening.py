"""Tests for the whitening / contrast-normalization family
(CreateImages.m modes + contrast_normalization helpers)."""
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.data import whitening
from ccsc_code_iccv2017_tpu.data.images import (
    gaussian_kernel,
    local_contrast_normalize,
    rconv2,
)


def _stack(n=6, side=24, seed=0):
    from scipy.ndimage import gaussian_filter

    r = np.random.default_rng(seed)
    return np.stack(
        [
            gaussian_filter(r.normal(size=(side, side)), 1.5).astype(
                np.float32
            )
            for _ in range(n)
        ]
    )


def test_rconv2_matches_reflect_conv():
    r = np.random.default_rng(1)
    x = r.normal(size=(10, 10))
    k = r.normal(size=(3, 3))
    out = rconv2(x, k)
    from scipy.signal import convolve2d

    ref = convolve2d(np.pad(x, 1, mode="symmetric"), k, mode="valid")
    np.testing.assert_allclose(out, ref, rtol=1e-10)
    assert out.shape == x.shape


def test_local_cn_matches_reference_formula():
    """Oracle test for the local_cn mode (CreateImages.m:299-370):
    (x - G*x) / max(sqrt(G*x^2 - (G*x)^2), median-floor)."""
    r = np.random.default_rng(2)
    img = np.concatenate(
        [r.normal(size=(32, 16)) * 5.0, r.normal(size=(32, 16)) * 0.1],
        axis=1,
    ).astype(np.float32)
    out = local_contrast_normalize(img)

    k = gaussian_kernel()  # fspecial('gaussian',[13 13],3*1.591)
    dim = img.astype(np.float64)
    lmn = rconv2(dim, k)
    lstd = np.sqrt(np.maximum(rconv2(dim * dim, k) - lmn * lmn, 0.0))
    th = np.median(lstd)
    lstd = np.maximum(lstd, th)
    expected = (dim - lmn) / lstd
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
    # regions above the median floor end up near unit local std
    def local_std(x):
        m = rconv2(x.astype(np.float64), k)
        v = np.maximum(rconv2(x.astype(np.float64) ** 2, k) - m * m, 0)
        return np.sqrt(v)

    assert 0.3 < local_std(out)[:, :12].mean() < 3.0


def test_zca_image_whitening_decorrelates():
    X = _stack(n=8)
    Xw = whitening.zca_whiten_images(X, eps=1e-6)
    F = Xw.reshape(8, -1).astype(np.float64)
    F -= F.mean(axis=0)
    G = F @ F.T / F.shape[1]
    off = G - np.diag(np.diag(G))
    assert np.abs(off).max() < np.abs(np.diag(G)).mean() * 0.2


def test_pca_whitening_flattens_spectrum():
    X = _stack(n=8, seed=3)
    Xw = whitening.pca_whiten_images(X, eps=1e-6)
    Fw = Xw.reshape(8, -1).astype(np.float64)
    s = np.linalg.svd(Fw - Fw.mean(0), compute_uv=False)
    # nonzero singular values nearly equal after whitening
    s = s[s > s[0] * 1e-3]
    assert s.min() / s.max() > 0.5


def test_inv_f_whiten_dewhiten_roundtrip():
    """dewhiten is a right-inverse on the whitened range: re-whitening
    its output reproduces the whitened image (exact recovery of x is
    impossible — the rho*exp(-(rho/f0)^4) filter suppresses DC and the
    far high band below float precision)."""
    img = _stack(n=1, side=32, seed=4)[0]
    w = whitening.inv_f_whiten(img)
    back = whitening.inv_f_dewhiten(w)
    w2 = whitening.inv_f_whiten(back)
    np.testing.assert_allclose(w2, w, atol=2e-3 * np.abs(w).max())


def test_sep_mean():
    X = _stack(n=5, seed=5)
    C, mu = whitening.sep_mean(X)
    np.testing.assert_allclose(C.mean(axis=0), 0.0, atol=1e-6)
    np.testing.assert_allclose(C + mu, X, rtol=1e-4, atol=1e-6)


def test_sep_mean_mean_image_plumbed_through_loader():
    """load_images(return_info=True) surfaces the dataset mean the
    reference keeps for re-addition (CreateImages.m:640-646) instead of
    dropping it; centered + mean reconstructs the input."""
    from ccsc_code_iccv2017_tpu.data.images import load_images

    X = _stack(n=5, seed=8)
    C, info = load_images(
        X, contrast_normalize="sep_mean", return_info=True
    )
    assert "mean_image" in info
    np.testing.assert_allclose(
        C + info["mean_image"], X, rtol=1e-4, atol=1e-5
    )
    # modes without undo state return an empty info dict
    _, info2 = load_images(X, return_info=True)
    assert info2 == {}
    # the default single-return signature is unchanged
    C2 = load_images(X, contrast_normalize="sep_mean")
    np.testing.assert_allclose(C2, C)


def test_sep_mean_mean_image_follows_layout():
    """For color stacks the mean image is re-oriented with the layout
    so `stack + mean_image` undoes the centering in every layout."""
    from ccsc_code_iccv2017_tpu.data.images import load_images

    rng = np.random.default_rng(9)
    X = rng.uniform(0.1, 1.0, (4, 8, 8, 3)).astype(np.float32)
    for layout in ("channels_last", "reduce", "batch"):
        C, info = load_images(
            X, contrast_normalize="sep_mean", color="rgb",
            layout=layout, return_info=True,
        )
        undone = C + info["mean_image"]
        ref = load_images(X, color="rgb", layout=layout)
        np.testing.assert_allclose(undone, ref, rtol=1e-4, atol=1e-5)


def test_laplacian_and_box_modes_run():
    img = _stack(n=1, seed=6)[0]
    lap = whitening.laplacian_cn(img)
    assert lap.shape == img.shape and np.isfinite(lap).all()
    box = whitening.box_cn(img, size=5)
    assert box.shape == img.shape and np.isfinite(box).all()


def test_zca_patch_whitening_runs():
    X = _stack(n=4, seed=7)
    out = whitening.zca_whiten_patches(X, patch=5, num_patches=2000)
    assert out.shape == X.shape and np.isfinite(out).all()


def test_zca_conv_filter_pair_inverts():
    """region_zca.m intent: the whitening and dewhitening conv kernels
    are approximate inverses — their convolution is close to a delta,
    and whiten->dewhiten approximately restores smooth images away from
    the boundary."""
    from scipy.signal import convolve2d

    from ccsc_code_iccv2017_tpu.data.whitening import (
        zca_conv_dewhiten,
        zca_conv_filters,
        zca_whiten_patches,
    )

    r = np.random.default_rng(0)
    # smooth correlated images (what whitening is for)
    from scipy.ndimage import gaussian_filter

    stack = np.stack(
        [
            gaussian_filter(r.normal(size=(48, 48)), 2.0)
            for _ in range(6)
        ]
    ).astype(np.float32)
    wk, dk = zca_conv_filters(stack, patch=7, num_patches=4000)
    comp = convolve2d(wk, dk, mode="full")
    c = comp.shape[0] // 2
    peak = comp[c, c]
    off = comp.copy()
    off[c, c] = 0.0
    assert abs(peak) > 5 * np.abs(off).max()

    white = zca_whiten_patches(stack, patch=7, num_patches=4000)
    back = zca_conv_dewhiten(white, dk)
    m = (slice(None), slice(10, -10), slice(10, -10))
    denom = np.abs(stack[m]).mean()
    err = np.abs(back[m] - stack[m]).mean() / denom
    assert err < 0.35, err
