"""Native (C++) preprocessing runtime vs the numpy reference path."""
import os

import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.data import native
from ccsc_code_iccv2017_tpu.data.images import local_contrast_normalize

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def test_local_cn_matches_numpy():
    r = np.random.default_rng(0)
    imgs = r.normal(size=(4, 48, 48)).astype(np.float32)
    out_c = native.local_cn_batch(imgs)
    out_py = np.stack([local_contrast_normalize(i) for i in imgs])
    # small differences: float32 accumulation + lower-middle vs averaged
    # median convention
    np.testing.assert_allclose(out_c, out_py, atol=5e-3)


def test_zero_mean_batch():
    r = np.random.default_rng(1)
    imgs = (r.normal(size=(3, 16, 16)) + 5.0).astype(np.float32)
    out = native.zero_mean_batch(imgs)
    np.testing.assert_allclose(out.mean(axis=(1, 2)), 0.0, atol=1e-5)
    np.testing.assert_allclose(
        out, imgs - imgs.mean(axis=(1, 2), keepdims=True), atol=1e-5
    )


def test_input_not_mutated():
    r = np.random.default_rng(2)
    imgs = r.normal(size=(2, 20, 20)).astype(np.float32)
    keep = imgs.copy()
    native.local_cn_batch(imgs)
    np.testing.assert_array_equal(imgs, keep)


def test_smooth_fill_matches_numpy():
    from ccsc_code_iccv2017_tpu.data.images import gaussian_kernel, rconv2

    r = np.random.default_rng(3)
    b = r.uniform(0.0, 1.0, (3, 32, 32)).astype(np.float32)
    mask = (r.uniform(size=b.shape) > 0.5).astype(np.float32)
    out_c = native.smooth_fill_batch(b, mask)
    k = gaussian_kernel()
    out_py = np.stack(
        [
            rconv2(bi * mi, k) / np.maximum(rconv2(mi, k), 1e-6)
            for bi, mi in zip(b, mask)
        ]
    )
    np.testing.assert_allclose(out_c, out_py, atol=2e-5)
    assert np.isfinite(out_c).all()
    # fully observed mask degenerates to plain Gaussian smoothing
    ones = np.ones_like(b)
    np.testing.assert_allclose(
        native.smooth_fill_batch(b, ones),
        np.stack([rconv2(bi, k) for bi in b]),
        atol=2e-5,
    )


def test_native_selftest_and_tsan():
    """C++ self-test harness; TSAN build is the framework's
    race-detection pass (skipped if the toolchain lacks tsan)."""
    import subprocess

    root = os.path.join(os.path.dirname(__file__), "..", "native")
    r = subprocess.run(
        ["make", "-C", root, "selftest"], capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr
    assert "ccsc_selftest: OK" in r.stdout
    t = subprocess.run(
        ["make", "-C", root, "tsan"], capture_output=True, text=True,
        timeout=600,
    )
    if t.returncode != 0 and "fsanitize" in (t.stderr or ""):
        pytest.skip("toolchain lacks ThreadSanitizer")
    assert t.returncode == 0, t.stderr
    assert "WARNING: ThreadSanitizer" not in t.stdout + t.stderr
