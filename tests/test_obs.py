"""Run telemetry subsystem (utils.obs + LearnConfig.metrics_dir):

- event-stream schema round-trip and crash-truncation tolerance;
- the acceptance contract: a consensus learn under outer_chunk=4 +
  donate_state=True emits a complete stream (run_meta, >=1 step record
  per chunk, compile events, summary) while executing the SAME number
  of dispatches and readback fences as an uninstrumented run;
- on-device extra scalars (ObsExtras) present in step records;
- the compile listener fires on a forced shape change and the summary
  flags the recompile;
- per-host heartbeats, including a real 2-process run writing into a
  shared metrics dir;
- masked / streaming / reconstruction streams;
- scripts/obs_report.py renders a real stream without error;
- bench.py records carry git_sha + degraded + event_stream provenance;
- the no-bare-print lint over the package (console output must route
  through the obs tier so terminal and stream cannot drift);
- the use_pallas no-op warning (VERDICT weak #6).
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom, SolveConfig
from ccsc_code_iccv2017_tpu.parallel import consensus
from ccsc_code_iccv2017_tpu.utils import obs

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ccsc_code_iccv2017_tpu",
)


def _b2d(n=8, size=16, seed=0):
    r = np.random.default_rng(seed)
    return jnp.asarray(r.normal(size=(n, size, size)).astype(np.float32))


CFG = dict(
    max_it=6, max_it_d=2, max_it_z=2, num_blocks=2, rho_d=500.0,
    rho_z=10.0, lambda_prior=0.1, verbose="none", track_objective=True,
    tol=0.0,
)


# ------------------------------------------------------------------
# event stream primitives
# ------------------------------------------------------------------

def test_event_stream_schema_roundtrip(tmp_path):
    d = str(tmp_path / "metrics")
    run = obs.start_run(
        d, algorithm="unit", verbose="none", workload="roundtrip"
    )
    try:
        run.step(it=1, obj_d=1.5, obj_z=2.5, d_diff=0.1, z_diff=0.2)
        run.event("heartbeat", step=1, fence_latency_s=0.01)
        run.chunk(0, 4, 4, 2.0)
    finally:
        run.close(status="ok", iterations=1)
    events = obs.read_events(d)
    types = [e["type"] for e in events]
    assert types[0] == "run_meta" and types[-1] == "summary"
    meta = events[0]
    assert meta["algorithm"] == "unit"
    assert meta["workload"] == "roundtrip"
    assert meta["platform"] == "cpu"
    assert "jax_version" in meta and "hostname" in meta
    step = next(e for e in events if e["type"] == "step")
    assert step["it"] == 1 and step["obj_z"] == 2.5
    assert all("t" in e and "host" in e for e in events)
    roof = next(e for e in events if e["type"] == "roofline")
    assert roof["it_per_sec"] == pytest.approx(2.0)
    summary = events[-1]
    assert summary["status"] == "ok" and "compile" in summary


def test_crash_truncation_drops_partial_line(tmp_path):
    d = str(tmp_path / "metrics")
    run = obs.start_run(d, algorithm="unit", verbose="none")
    run.step(it=1, obj_z=1.0)
    run.step(it=2, obj_z=2.0)
    run.close()
    path = os.path.join(d, os.listdir(d)[0])
    with open(path, "a") as f:
        f.write('{"type": "step", "it": 3, "obj')  # torn mid-record
    events = obs.read_events(d)
    assert [e["it"] for e in events if e["type"] == "step"] == [1, 2]
    # a resumed writer appending after the torn line keeps working —
    # including its FIRST record (the writer newline-terminates a torn
    # tail on open instead of appending run_meta onto it)
    run2 = obs.start_run(d, algorithm="unit", verbose="none")
    run2.step(it=4, obj_z=4.0)
    run2.close()
    events = obs.read_events(d)
    assert [e["it"] for e in events if e["type"] == "step"] == [1, 2, 4]
    assert len([e for e in events if e["type"] == "run_meta"]) == 2


def test_event_tail_rotation_mid_tail(tmp_path):
    """EventTail under capture-style rotation: the writer rolls over
    to a NEW segment file mid-tail — the tail must pick the fresh
    file up on its next poll, consume only whole lines from both, and
    never re-read or skip records."""
    d = str(tmp_path / "stream")
    os.makedirs(d)

    def seg(i):
        return os.path.join(d, f"events-{i:04d}.jsonl")

    def w(path, recs, torn=None):
        with open(path, "a") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
            if torn is not None:
                f.write(torn)  # no newline: the crash window

    tail = obs.EventTail(d)
    w(seg(0), [{"t": 1.0, "type": "step", "it": 1}])
    assert [r["it"] for r in tail.poll()] == [1]
    # segment 0 gains one whole record + a torn tail, and the writer
    # rotates: segment 1 appears with its own records
    w(seg(0), [{"t": 2.0, "type": "step", "it": 2}],
      torn='{"t": 2.5, "type": "step", "i')
    w(seg(1), [{"t": 3.0, "type": "step", "it": 3}])
    got = tail.poll()
    assert [r["it"] for r in got] == [2, 3]  # torn line NOT consumed
    # the torn line is completed later (resumed writer terminates it)
    # and both files keep growing — the tail resumes cleanly from its
    # per-file offsets
    with open(seg(0), "a") as f:
        f.write("\n")
    w(seg(0), [{"t": 4.0, "type": "step", "it": 4}])
    w(seg(1), [{"t": 5.0, "type": "step", "it": 5}])
    got = tail.poll()
    # the completed line parses as garbage-free records only: the
    # torn fragment became a whole (but truncated-JSON) line and is
    # dropped, never welded onto later records
    assert [r["it"] for r in got if "it" in r] == [4, 5]
    assert tail.poll() == []  # idempotent at rest


def test_payload_index_torn_tail(tmp_path):
    """serve.capture's payload index under the same crash window: a
    torn final line is dropped by the reader, and a recorder
    re-opened on the directory repairs the tail before appending (no
    welded records)."""
    from ccsc_code_iccv2017_tpu.serve import capture as cap

    d = str(tmp_path / "capture")
    rec = cap.WorkloadRecorder(d)
    a = np.arange(9, dtype=np.float32).reshape(3, 3)
    rec.record_submit("k0", None, a)
    rec.close()
    idx_path = os.path.join(d, "payloads.jsonl")
    with open(idx_path, "a") as f:
        f.write('{"sha": "deadbeef", "shape": [3')  # torn
    idx = cap.read_payload_index(d)
    assert len(idx) == 1 and "deadbeef" not in idx
    # a re-opened recorder terminates the torn tail; its new index
    # entry parses whole and the old one survives
    rec2 = cap.WorkloadRecorder(d)
    rec2.record_submit("k1", None, a * 2.0)
    rec2.close()
    idx = cap.read_payload_index(d)
    assert len(idx) == 2
    shas = set(idx)
    for sha in shas:
        assert np.asarray(cap.load_payload(d, sha)).shape == (3, 3)
    # segments survived the reopen too: both requests read back
    # (t_rel is per-recorder-epoch, so cross-reopen order is not
    # asserted)
    w = cap.read_workload(d)
    assert sorted(r["key"] for r in w) == ["k0", "k1"]


def test_null_run_is_inert(tmp_path, capsys):
    run = obs.start_run(None, algorithm="unit", verbose="brief")
    try:
        assert not run.active
        run.step(it=1, obj_z=1.0)
        run.console("hello", tier="brief")
        run.console("hidden", tier="all")
    finally:
        run.close()
    out = capsys.readouterr().out
    assert "hello" in out and "hidden" not in out


# ------------------------------------------------------------------
# acceptance: complete stream + dispatch/fence parity under
# outer_chunk=4 + donate_state
# ------------------------------------------------------------------

def _instrument(counts):
    """Wrap the consensus step/eval builders and the readback fence
    with call counters; returns the originals for restore."""
    orig_chunk = consensus.make_outer_chunk_step
    orig_step = consensus.make_outer_step
    orig_eval = consensus.make_eval_fn
    orig_rb = consensus._readback

    def counting(builder, key):
        def build(*a, **k):
            fn = builder(*a, **k)

            def call(*aa, **kk):
                counts[key] += 1
                return fn(*aa, **kk)

            return call

        return build

    consensus.make_outer_chunk_step = counting(orig_chunk, "chunk")
    consensus.make_outer_step = counting(orig_step, "step")
    consensus.make_eval_fn = counting(orig_eval, "eval")

    def rb(tree):
        counts["fence"] += 1
        return orig_rb(tree)

    consensus._readback = rb
    return orig_chunk, orig_step, orig_eval, orig_rb


def _restore(origs):
    (
        consensus.make_outer_chunk_step,
        consensus.make_outer_step,
        consensus.make_eval_fn,
        consensus._readback,
    ) = origs


def _counted_learn(cfg):
    counts = {"chunk": 0, "step": 0, "eval": 0, "fence": 0}
    origs = _instrument(counts)
    try:
        res = consensus.learn(
            _b2d(), ProblemGeom((3, 3), 4), cfg,
            key=jax.random.PRNGKey(0),
        )
    finally:
        _restore(origs)
    return res, counts


def test_chunked_stream_complete_and_dispatch_parity(tmp_path):
    """THE acceptance criterion: with --metrics-dir set, the chunked+
    donated consensus learn emits run metadata, >=1 step record per
    chunk, compile events and a final summary, while executing exactly
    as many dispatches and readback fences as the uninstrumented run."""
    base = dict(CFG, outer_chunk=4, donate_state=True)
    ref, plain = _counted_learn(LearnConfig(**base))
    d = str(tmp_path / "metrics")
    os.environ["CCSC_OBS_HEARTBEAT_S"] = "0"
    try:
        res, instr = _counted_learn(LearnConfig(**base, metrics_dir=d))
    finally:
        os.environ.pop("CCSC_OBS_HEARTBEAT_S", None)

    # same trajectory...
    np.testing.assert_allclose(
        np.asarray(ref.d), np.asarray(res.d), atol=1e-6
    )
    # ...and exactly the same dispatch/fence counts: telemetry rides
    # the existing chunk fence, it never adds one
    assert instr == plain
    assert plain["chunk"] == 2  # 6 iters as chunks of 4 + 2
    assert plain["fence"] == 2

    events = obs.read_events(d)
    by = {}
    for e in events:
        by.setdefault(e["type"], []).append(e)
    # complete stream: metadata, steps, compiles, summary
    assert len(by["run_meta"]) == 1
    meta = by["run_meta"][0]
    assert meta["algorithm"] == "consensus"
    assert meta["config"]["outer_chunk"] == 4
    assert meta["config"]["donate_state"] is True
    assert meta["fingerprint"]
    steps = by["step"]
    assert [s["it"] for s in steps] == [1, 2, 3, 4, 5, 6]
    # >= 1 step record per chunk and a roofline record per chunk
    assert len(by["roofline"]) == 2
    roof = by["roofline"][0]
    assert roof["n_adopted"] == 4 and roof["it_per_sec"] > 0
    assert "mfu" in roof and "hbm_frac" in roof  # scored vs perfmodel
    assert roof["bound_it_per_sec"] > 0  # the roofline ceiling itself
    assert len(by["compile"]) >= 1
    assert by["heartbeat"], "chunk fences emit heartbeats"
    summary = by["summary"][-1]
    assert summary["status"] == "ok"
    assert summary["iterations"] == 6
    assert summary["compile"]["n_compiles"] >= 1


def test_step_records_carry_on_device_extras(tmp_path):
    """ObsExtras (objective split, consensus disagreement, non-finite
    count) accumulate inside the jitted scan and land in every step
    record."""
    d = str(tmp_path / "metrics")
    consensus.learn(
        _b2d(), ProblemGeom((3, 3), 4),
        LearnConfig(**dict(CFG, outer_chunk=3), metrics_dir=d),
        key=jax.random.PRNGKey(0),
    )
    steps = [e for e in obs.read_events(d) if e["type"] == "step"]
    assert len(steps) == 6
    for s in steps:
        assert s["nonfinite_z"] == 0
        assert s["consensus_dis"] >= 0.0
        # the split must reassemble the recorded objective
        assert s["obj_fid"] + s["obj_l1"] == pytest.approx(
            s["obj_z"], rel=1e-5
        )


def test_per_step_driver_also_emits(tmp_path):
    """The un-chunked (outer_chunk=1, no donation) driver emits the
    same record family."""
    d = str(tmp_path / "metrics")
    consensus.learn(
        _b2d(), ProblemGeom((3, 3), 4),
        LearnConfig(**dict(CFG, max_it=2), metrics_dir=d),
        key=jax.random.PRNGKey(0),
    )
    events = obs.read_events(d)
    types = {e["type"] for e in events}
    assert {"run_meta", "step", "roofline", "compile", "summary"} <= types
    steps = [e for e in events if e["type"] == "step"]
    assert len(steps) == 2 and "consensus_dis" in steps[0]


# ------------------------------------------------------------------
# compile / recompile tracking
# ------------------------------------------------------------------

def test_recompile_listener_fires_on_shape_change(tmp_path):
    d = str(tmp_path / "metrics")
    run = obs.start_run(d, algorithm="unit", verbose="none")
    try:
        @jax.jit
        def poly_fn(x):
            return (x * 2.0).sum()

        float(poly_fn(jnp.ones((4,))))
        float(poly_fn(jnp.ones((8,))))  # forced shape change -> recompile
    finally:
        run.close()
    events = obs.read_events(d)
    compiles = [
        e for e in events
        if e["type"] == "compile"
        and e.get("fun_name") and "poly_fn" in e["fun_name"]
    ]
    assert len(compiles) >= 2, compiles
    assert all(c["duration_s"] > 0 for c in compiles)
    # names + abstract shapes harvested from the debug logs
    shapes = [c["shapes"] for c in compiles if c.get("shapes")]
    assert any("float32[4]" in s for s in shapes)
    assert any("float32[8]" in s for s in shapes)
    summary = [e for e in events if e["type"] == "summary"][-1]
    assert any(
        "poly_fn" in f for f in summary["compile"]["recompiled_funs"]
    )


def test_compile_monitor_uninstalls_cleanly(tmp_path):
    from jax._src import monitoring as _mon

    before = len(_mon._event_duration_secs_listeners)
    run = obs.start_run(str(tmp_path / "m"), algorithm="u", verbose="none")
    assert len(_mon._event_duration_secs_listeners) == before + 1
    run.close()
    assert len(_mon._event_duration_secs_listeners) == before


def test_compile_monitor_hub_out_of_order_close(tmp_path):
    """Concurrently open runs (a serving fleet holds N+1) share ONE
    process-wide set of compile-harvest hooks via the monitor hub:
    the first run's close must neither remove the hooks from under
    the survivor nor restore a logger state captured mid-flight —
    the TRUE pre-install state comes back only when the last
    subscriber leaves."""
    import logging as _logging

    from jax._src import monitoring as _mon

    lg = _logging.getLogger("jax._src.dispatch")
    level0, prop0 = lg.level, lg.propagate
    before = len(_mon._event_duration_secs_listeners)
    r1 = obs.start_run(str(tmp_path / "a"), algorithm="u", verbose="none")
    r2 = obs.start_run(str(tmp_path / "b"), algorithm="u", verbose="none")
    # one shared install, not one per run
    assert len(_mon._event_duration_secs_listeners) == before + 1
    r1.close()  # out of order: the FIRST-opened run closes first
    # the survivor still harvests: hooks stay installed and the
    # dispatch logger still emits the DEBUG records it reads
    assert len(_mon._event_duration_secs_listeners) == before + 1
    assert lg.getEffectiveLevel() <= _logging.DEBUG
    r2.close()
    assert len(_mon._event_duration_secs_listeners) == before
    assert lg.level == level0 and lg.propagate == prop0


def test_start_run_without_compile_monitor(tmp_path):
    from jax._src import monitoring as _mon

    before = len(_mon._event_duration_secs_listeners)
    run = obs.start_run(
        str(tmp_path / "m"), algorithm="u", verbose="none",
        compile_monitor=False,
    )
    try:
        assert run.compile_monitor is None
        assert len(_mon._event_duration_secs_listeners) == before
        run.event("probe", x=1)
    finally:
        run.close()
    events = obs.read_events(str(tmp_path / "m"))
    assert any(e["type"] == "probe" for e in events)


# ------------------------------------------------------------------
# heartbeats
# ------------------------------------------------------------------

def test_heartbeat_cadence(tmp_path):
    d = str(tmp_path / "m")
    w = obs.EventWriter(os.path.join(d, "events-p00000.jsonl"))
    run = obs.Run(w, verbose="none", heartbeat_every_s=0.0)
    for i in range(3):
        run.heartbeat(i + 1, 0.5)
    run.close()
    beats = [
        e for e in obs.read_events(d) if e["type"] == "heartbeat"
    ]
    assert [b["step"] for b in beats] == [1, 2, 3]
    assert beats[0]["fence_latency_s"] == pytest.approx(0.5)

    d2 = str(tmp_path / "m2")
    w2 = obs.EventWriter(os.path.join(d2, "events-p00000.jsonl"))
    run2 = obs.Run(w2, verbose="none", heartbeat_every_s=3600.0)
    for i in range(5):
        run2.heartbeat(i + 1, 0.1)
    run2.close()
    beats2 = [
        e for e in obs.read_events(d2) if e["type"] == "heartbeat"
    ]
    assert len(beats2) == 1  # cadence suppresses the rest


def test_two_process_heartbeats_shared_dir(tmp_path):
    """Two REAL processes bootstrap via distributed.initialize and
    write heartbeats into ONE shared metrics dir — each host its own
    events file, each record carrying its process index. (Runs the
    learner locally per process: this jaxlib's CPU backend has no
    multi-process collectives, but per-host telemetry needs none.)"""
    import socket
    import subprocess
    import sys
    import textwrap

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import os, sys
        pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
        os.environ["CCSC_OBS_HEARTBEAT_S"] = "0"
        os.environ.pop("JAX_PLATFORMS", None)
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ccsc_code_iccv2017_tpu.parallel import distributed
        distributed.initialize(
            f"127.0.0.1:{port}", num_processes=2, process_id=pid
        )
        assert jax.process_count() == 2
        import numpy as np, jax.numpy as jnp
        from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
        from ccsc_code_iccv2017_tpu.models import learn as learn_mod
        b = np.random.default_rng(7).normal(
            size=(4, 12, 12)).astype(np.float32)
        cfg = LearnConfig(
            max_it=2, max_it_d=1, max_it_z=1, num_blocks=2,
            rho_d=50.0, rho_z=2.0, verbose="none",
            track_objective=True, metrics_dir=outdir + "/metrics",
        )
        learn_mod.learn(jnp.asarray(b), geom=ProblemGeom((3, 3), 4),
                        cfg=cfg, key=jax.random.PRNGKey(0))
    """ % "/root/repo"))

    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(port),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o[-3000:]

    files = sorted(os.listdir(tmp_path / "metrics"))
    assert files == ["events-p00000.jsonl", "events-p00001.jsonl"]
    events = obs.read_events(str(tmp_path / "metrics"))
    beats = [e for e in events if e["type"] == "heartbeat"]
    assert {b["host"] for b in beats} == {0, 1}
    metas = [e for e in events if e["type"] == "run_meta"]
    assert {m["process_index"] for m in metas} == {0, 1}
    assert all(m["process_count"] == 2 for m in metas)


# ------------------------------------------------------------------
# masked / streaming / reconstruction streams
# ------------------------------------------------------------------

def test_masked_learner_emits_stream(tmp_path):
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked

    d = str(tmp_path / "metrics")
    b = _b2d(n=2, size=12)
    learn_masked(
        b, ProblemGeom((3, 3), 3),
        LearnConfig(
            max_it=3, max_it_d=1, max_it_z=1, verbose="none",
            track_objective=True, tol=0.0, outer_chunk=2,
            donate_state=True, metrics_dir=d,
        ),
        key=jax.random.PRNGKey(0),
    )
    events = obs.read_events(d)
    by = {}
    for e in events:
        by.setdefault(e["type"], []).append(e)
    assert by["run_meta"][0]["algorithm"] == "masked_admm"
    assert len(by["step"]) == 3
    assert by["roofline"]  # it/s only (no masked cost model)
    assert by["summary"][-1]["status"] == "ok"


def test_streaming_learner_emits_stream(tmp_path):
    from ccsc_code_iccv2017_tpu.parallel.streaming import learn_streaming

    d = str(tmp_path / "metrics")
    b = np.asarray(_b2d(n=4, size=12))
    learn_streaming(
        b, ProblemGeom((3, 3), 3),
        LearnConfig(
            max_it=4, max_it_d=1, max_it_z=1, num_blocks=2,
            rho_d=50.0, rho_z=2.0, verbose="none",
            track_objective=True, tol=0.0, outer_chunk=2,
            metrics_dir=d,
        ),
        key=jax.random.PRNGKey(0),
    )
    events = obs.read_events(d)
    by = {}
    for e in events:
        by.setdefault(e["type"], []).append(e)
    assert by["run_meta"][0]["algorithm"] == "consensus_streaming"
    assert len(by["step"]) == 4
    roof = by["roofline"][0]
    assert roof["length"] == 2 and "mfu" in roof  # consensus cost model
    assert by["summary"][-1]["iterations"] == 4


def test_reconstruction_emits_stream(tmp_path):
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )

    d = str(tmp_path / "metrics")
    geom = ProblemGeom((3, 3), 2)
    r = np.random.default_rng(0)
    b = jnp.asarray(r.normal(size=(1, 10, 10)).astype(np.float32))
    filt = jnp.asarray(r.normal(size=(2, 3, 3)).astype(np.float32))
    res = reconstruct(
        b, filt, ReconstructionProblem(geom),
        SolveConfig(max_it=4, verbose="none", metrics_dir=d),
    )
    events = obs.read_events(d)
    by = {}
    for e in events:
        by.setdefault(e["type"], []).append(e)
    assert by["run_meta"][0]["algorithm"] == "reconstruct"
    n_it = int(res.trace.num_iters)
    # step records are 1-based per iteration, like the learners'
    assert [s["it"] for s in by["step"]] == list(
        range(1, min(n_it + 1, 5))
    )
    assert by["summary"][-1]["iterations"] == n_it


# ------------------------------------------------------------------
# obs_report rendering
# ------------------------------------------------------------------

def test_obs_report_renders_real_stream(tmp_path, capsys):
    import importlib.util

    d = str(tmp_path / "metrics")
    os.environ["CCSC_OBS_HEARTBEAT_S"] = "0"
    try:
        consensus.learn(
            _b2d(), ProblemGeom((3, 3), 4),
            LearnConfig(
                **dict(CFG, outer_chunk=4, donate_state=True),
                metrics_dir=d,
            ),
            key=jax.random.PRNGKey(0),
        )
    finally:
        os.environ.pop("CCSC_OBS_HEARTBEAT_S", None)
    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(PKG_ROOT), "scripts",
                     "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main([d])
    out = capsys.readouterr().out
    for section in ("RUN", "PHASES", "STEPS", "ROOFLINE", "COMPILES",
                    "HOSTS", "SUMMARY"):
        assert section in out, section
    assert "algorithm     consensus" in out
    assert "it/s" in out
    # renders mid-run streams too (no summary yet, torn tail)
    with open(os.path.join(d, "events-p00000.jsonl"), "a") as f:
        f.write('{"type": "step"')
    mod.main([d])
    assert "SUMMARY" in capsys.readouterr().out


# ------------------------------------------------------------------
# bench provenance
# ------------------------------------------------------------------

def test_bench_emit_carries_provenance(capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_prov", os.path.join(os.path.dirname(PKG_ROOT), "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    r = {
        "iters_per_sec": 1.0, "n": 8, "size": 24, "k": 8, "blocks": 2,
        "platform": "cpu", "event_stream": "/tmp/x/events-p00000.jsonl",
    }
    bench.emit(r, degraded=True)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["degraded"] is True
    assert "git_sha" in out  # may be None outside a git checkout
    assert out["event_stream"] == "/tmp/x/events-p00000.jsonl"
    bench.emit(dict(r, event_stream=None), degraded=False)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["degraded"] is False and out["event_stream"] is None


# ------------------------------------------------------------------
# lint: no bare prints in the library (apps/ CLI surface exempt)
# ------------------------------------------------------------------


def test_no_bare_prints_in_package():
    """Thin wrapper over the migrated `bare-print` analysis check
    (ccsc_code_iccv2017_tpu/analysis/conventions.py) — kept here so a
    regression still fails in the telemetry test file it historically
    lived in. The full suite runs in tests/test_analysis.py."""
    from ccsc_code_iccv2017_tpu.analysis import core

    project = core.Project(
        [PKG_ROOT], repo_root=os.path.dirname(PKG_ROOT)
    )
    offenders = core.run_checks(project, ["bare-print"])
    assert not offenders, (
        "bare print() in library code — use utils.obs console tiers "
        "instead:\n" + "\n".join(f.render() for f in offenders)
    )


# ------------------------------------------------------------------
# use_pallas fallback warning (VERDICT weak #6 discipline, kept
# through the r10 re-promotion: callers who asked for the Pallas
# route must hear when it could not engage)
# ------------------------------------------------------------------

def test_use_pallas_fallback_warns_once():
    from ccsc_code_iccv2017_tpu.ops import freq_solvers

    # W == 2: a matrix inner inverse — outside the rank-1 kernel's
    # coverage, so use_pallas=True falls back to the einsum path
    dhat = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 2, 5))
        + 1j * np.random.default_rng(1).normal(size=(2, 2, 5))
    ).astype(jnp.complex64)
    kern = freq_solvers.precompute_z_kernel(dhat, 1.0)
    xi1 = jnp.zeros((1, 2, 5), jnp.complex64)
    xi2 = jnp.zeros((1, 2, 5), jnp.complex64)
    freq_solvers._use_pallas_warned = False
    with pytest.warns(UserWarning, match="fell back to the einsum"):
        freq_solvers.solve_z(kern, xi1, xi2, 1.0, use_pallas=True)
    # one-time: a second call stays silent
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        freq_solvers.solve_z(kern, xi1, xi2, 1.0, use_pallas=True)
    freq_solvers._use_pallas_warned = False
